"""End-to-end delta causality suite (ISSUE 14 acceptance): per-delta
trace identity through the serve stack, slow-delta forensics, the
fleet trace merge, and the default-off parity pins.

The load-bearing invariants:

* armed (JEPSEN_TPU_TRACE / _FLIGHT_RECORDER / _SLOW_DELTA_SECS), one
  admitted delta is ONE linked span chain tagged {delta_id, key,
  tenant, seq} — transport leg through WAL fsync through worker apply
  through verdict publish — and the id survives WAL replay, replica
  migration, and adoption (it rides the transferred segments);
* the slow-delta ring captures a stage-by-stage breakdown whose
  shape (`backpressure/wal/queue/device/publish`) is what `jepsen
  report --slow` renders and /status surfaces;
* UNARMED, everything is byte-identical to the pre-tracing service:
  acks carry no delta_id, WAL records gain no field, /status gains
  no key.
"""

import json
import os

import pytest

from jepsen_tpu import obs
from jepsen_tpu.envflags import EnvFlagError
from jepsen_tpu.histories import rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.serve import CheckerService, DeltaWAL
from jepsen_tpu.serve.ring import Router


@pytest.fixture(autouse=True)
def _clean_obs_state(monkeypatch):
    for flag in ("JEPSEN_TPU_TRACE", "JEPSEN_TPU_FLIGHT_RECORDER",
                 "JEPSEN_TPU_SLOW_DELTA_SECS", "JEPSEN_TPU_FAULTS"):
        monkeypatch.delenv(flag, raising=False)
    obs.reset()
    obs.flight_reset()
    obs.drain_slow_deltas()
    yield
    obs.reset()
    obs.flight_reset()
    obs.drain_slow_deltas()


def _hist(seed=11, n=16):
    return list(rand_register_history(n_ops=n, n_processes=3,
                                      n_values=3, seed=seed))


# ------------------------------------------------ id lifecycle


def test_unarmed_service_is_byte_identical(tmp_path):
    """Parity pin: with every tracing flag unset, acks carry no
    delta_id, the WAL record bytes carry no "id" field, and /status
    has no slow-delta keys — the pre-tracing service, exactly."""
    h = _hist()
    svc = CheckerService(CASRegister(), wal_dir=str(tmp_path),
                         capacity=128)
    try:
        a = svc.submit("k", h[:8], timeout=30)
        assert a["accepted"] and "delta_id" not in a
        # an explicitly supplied delta_id is IGNORED while unarmed
        a2 = svc.submit("k", h[8:], timeout=30,
                        delta_id="should-vanish")
        assert a2["accepted"] and "delta_id" not in a2
        svc.drain(timeout=60)
        st = svc.status()
        assert "slow_deltas" not in st \
            and "slow_delta_secs" not in st
        seg = DeltaWAL(str(tmp_path)).segments("k")[0]
        for line in open(seg).read().splitlines()[1:]:
            assert '"id"' not in line
            # the record spells exactly the historical fields
            assert sorted(json.loads(line)) == ["ops", "seq"]
    finally:
        svc.close(drain=False)


def test_armed_ack_wal_and_span_chain(tmp_path, monkeypatch):
    """Tracing on: the ack returns the minted delta_id, the WAL
    record persists it, and the span chain carries it on the
    admit/wal legs and as delta_ids on the worker apply leg."""
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    obs.reset()
    h = _hist()
    svc = CheckerService(CASRegister(), wal_dir=str(tmp_path),
                         capacity=128)
    try:
        r = svc.submit("k", h[:8], wait=True, timeout=120)
        assert r.get("delta_id")
        did = r["delta_id"]
        # producer-supplied ids ride through
        r2 = svc.submit("k", h[8:], wait=True, timeout=120,
                        delta_id="my-own-id-1")
        assert r2["delta_id"] == "my-own-id-1"
        ids = DeltaWAL(str(tmp_path)).delta_ids("k")
        assert ids == {1: did, 2: "my-own-id-1"}
        spans = obs.tracer().spans()
        by_name = {}
        for s in spans:
            by_name.setdefault(s.name, []).append(s)
        admits = [s for s in by_name.get("serve.admit", ())
                  if s.args.get("delta_id") == did]
        assert admits and admits[0].args["seq"] == 1
        wals = [s for s in by_name.get("serve.wal", ())
                if s.args.get("delta_id") == did]
        assert wals
        applies = [s for s in by_name.get("serve.apply", ())
                   if did in (s.args.get("delta_ids") or ())]
        assert applies
        assert "serve.publish" in by_name
    finally:
        svc.close(drain=False)


def test_id_survives_restart_and_old_wal_synthesizes(tmp_path,
                                                     monkeypatch):
    """WAL replay keeps the stamped ids; records written WITHOUT ids
    (the pre-tracing on-disk format) replay with a synthesized stable
    id — back-compat, pinned on actual old-format bytes."""
    h = _hist()
    # write an OLD-format WAL (unarmed service)
    svc = CheckerService(CASRegister(), wal_dir=str(tmp_path),
                         capacity=128)
    svc.submit("old-k", h[:8], timeout=30)
    svc.drain(timeout=60)
    svc.close()
    wal = DeltaWAL(str(tmp_path))
    ids = wal.delta_ids("old-k")
    assert list(ids) == [1] and ids[1].startswith("wal-")
    assert wal.delta_ids("old-k") == ids      # deterministic
    # an armed restart replays it and continues the stream with
    # minted ids; the old delta's synthetic id tags the thaw replay
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    obs.reset()
    svc2 = CheckerService(CASRegister(), wal_dir=str(tmp_path),
                          capacity=128)
    try:
        r = svc2.submit("old-k", h[8:], wait=True, timeout=120)
        assert r.get("delta_id")
        ids2 = DeltaWAL(str(tmp_path)).delta_ids("old-k")
        assert ids2[1] == ids[1] and ids2[2] == r["delta_id"]
    finally:
        svc2.close(drain=False)


def test_migrated_delta_chain_reads_across_replicas(tmp_path,
                                                    monkeypatch):
    """The cross-replica acceptance at unit scale: a key admitted on
    one replica and migrated to another leaves delta_id-tagged spans
    on BOTH sides — the source's admit/wal legs and the destination's
    thaw/apply legs share the id (it rode the transferred WAL
    segments)."""
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    obs.reset()
    h = _hist()
    dirs = {n: str(tmp_path / n) for n in ("ra", "rb")}
    svcs = {n: CheckerService(CASRegister(), wal_dir=d, capacity=128)
            for n, d in dirs.items()}
    router = Router(svcs, dirs)
    try:
        key = "mig-k"
        src = router.owner(key)
        dst = [n for n in svcs if n != src][0]
        r = router.submit(key, h[:8], wait=True, timeout=120)
        did = r["delta_id"]
        router.migrate_key(key, dst)
        r2 = router.submit(key, h[8:], wait=True, timeout=120)
        assert "valid?" in r2
        # both replicas share this process's tracer; the chain still
        # proves the id crossed the boundary: a thaw/apply span
        # tagged with the ORIGINAL id exists beyond the source's own
        # admit/apply legs (the destination replayed it)
        tagged = [s for s in obs.tracer().spans()
                  if s.name in ("serve.thaw", "serve.apply")
                  and did in (s.args.get("delta_ids") or ())]
        assert len(tagged) >= 2, [(s.name, s.args) for s in tagged]
    finally:
        for s in svcs.values():
            s.close(drain=False)


# ------------------------------------------- slow-delta forensics


def test_slow_delta_ring_status_export_report(tmp_path, monkeypatch):
    """The forensics pipeline end to end: a tiny threshold makes
    every delta slow; the record carries the full stage breakdown;
    /status surfaces the ring; export_run drains it to
    slow_deltas.jsonl even with tracing OFF; `jepsen report --slow`
    renders it."""
    monkeypatch.setenv("JEPSEN_TPU_SLOW_DELTA_SECS", "0.00001")
    h = _hist()
    svc = CheckerService(CASRegister(), wal_dir=str(tmp_path / "w"),
                         capacity=128)
    try:
        r = svc.submit("slow-k", h[:8], wait=True, timeout=120)
        assert r.get("delta_id")   # the threshold alone arms ids
        svc.drain(timeout=60)
        st = svc.status()
        assert st["slow_delta_secs"] == pytest.approx(0.00001)
        recs = st["slow_deltas"]
        assert recs
        rec = recs[0]
        assert rec["delta_id"] == r["delta_id"]
        assert rec["key"] == "slow-k" and rec["seq"] == 1
        assert set(rec["stages"]) == {"backpressure", "wal", "queue",
                                      "device", "publish"}
        assert rec["slowest_stage"] in rec["stages"]
        # the wal stage is a measured fsync duration CONCURRENT with
        # queue/device (the worker never waits on the fsync), so the
        # stages may over-count total by at most the wal stage
        assert rec["total_secs"] >= (sum(rec["stages"].values())
                                     - rec["stages"]["wal"] - 1e-3)
        assert rec["verdict"] is not None
    finally:
        svc.close(drain=False)
    run_dir = tmp_path / "run"
    arts = obs.export_run(str(run_dir))
    assert arts and "slow_deltas" in arts
    lines = [json.loads(ln) for ln in
             open(os.path.join(str(run_dir), "slow_deltas.jsonl"))]
    assert lines and lines[0]["delta_id"]
    # drained: a second export writes nothing
    assert obs.export_run(str(tmp_path / "run2")) is None
    from jepsen_tpu.obs.search_report import report_main
    assert report_main(["--slow", "--run-dir", str(run_dir)]) == 0
    txt = open(os.path.join(str(run_dir), "slow_report.txt")).read()
    assert lines[0]["delta_id"] in txt and "device" in txt
    # no input -> exit 1, usage without a mode -> 254
    assert report_main(["--slow",
                        "--run-dir", str(tmp_path / "run2")]) == 1
    assert report_main(["--run-dir", str(run_dir)]) == 254


def test_slow_delta_worst_offender_flight_dump(tmp_path, monkeypatch):
    """The worst offender triggers a flight dump whose trigger block
    cross-references the slow-delta record (satellite: dumps embed
    the triggering delta_id/key/tenant)."""
    monkeypatch.setenv("JEPSEN_TPU_SLOW_DELTA_SECS", "0.00001")
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "1")
    obs.reset()
    obs.set_flight_dir(str(tmp_path / "flight"))
    h = _hist()
    svc = CheckerService(CASRegister(), capacity=128)
    try:
        svc.submit("fk", h[:8], wait=True, timeout=120)
    finally:
        svc.close(drain=False)
    dumps = [f for f in os.listdir(tmp_path / "flight")
             if f.startswith("flight_slow-delta")]
    assert dumps
    doc = json.load(open(tmp_path / "flight" / dumps[0]))
    trig = doc["flight"]["trigger"]
    assert trig["key"] == "fk" and trig["delta_id"] \
        and trig["stages"]["device"] >= 0


def test_slow_delta_ring_is_bounded_newest_wins():
    from jepsen_tpu.obs import export as export_mod
    for i in range(export_mod.SLOW_DELTA_MAX_RECORDS + 10):
        obs.record_slow_delta({"delta_id": f"d{i}",
                               "total_secs": 0.001})
    recs = obs.slow_delta_records()
    assert len(recs) == export_mod.SLOW_DELTA_MAX_RECORDS
    assert recs[-1]["delta_id"] == \
        f"d{export_mod.SLOW_DELTA_MAX_RECORDS + 9}"   # newest kept
    assert recs[0]["delta_id"] == "d10"               # oldest gone
    assert obs.drain_slow_deltas() and not obs.slow_delta_records()


def test_slow_delta_ring_scoped_per_service():
    """Two services in one process (the serve_smoke shape) must not
    read each other's forensics on /status, and one service's huge
    offender must not suppress the other's worst-offender flight
    dump — records are scoped, the drain stays process-wide."""
    obs.reset()
    obs.drain_slow_deltas()
    s1, s2 = object(), object()
    big = {"delta_id": "d-big", "key": "k1", "total_secs": 10.0}
    small = {"delta_id": "d-small", "key": "k2", "total_secs": 8.0}
    assert obs.record_slow_delta(big, scope=s1) is True
    # s2's FIRST offender is its own worst — s1's 10s must not mute it
    assert obs.record_slow_delta(small, scope=s2) is True
    assert [r["key"] for r in obs.slow_delta_records(s1)] == ["k1"]
    assert [r["key"] for r in obs.slow_delta_records(s2)] == ["k2"]
    # unscoped read and the run-artifact drain stay process-wide
    assert len(obs.slow_delta_records()) == 2
    assert [r["key"] for r in obs.drain_slow_deltas()] == ["k1", "k2"]
    assert obs.slow_delta_records() == []


def test_slow_delta_flag_is_validated(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_SLOW_DELTA_SECS", "quick")
    with pytest.raises(EnvFlagError):
        CheckerService(CASRegister(), capacity=128,
                       start_worker=False)
    monkeypatch.setenv("JEPSEN_TPU_SLOW_DELTA_SECS", "-1")
    with pytest.raises(EnvFlagError):
        CheckerService(CASRegister(), capacity=128,
                       start_worker=False)


# ------------------------------------------------ ingress parenting


def test_ingress_span_parents_service_chain(monkeypatch):
    """Satellite pin: the per-request Context.copy across the
    ingress's run_in_executor hop makes the service's serve.admit
    span a DESCENDANT of serve.ingress.request instead of an orphan
    root."""
    from jepsen_tpu.serve.ingress import DeltaIngress
    import urllib.request
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    obs.reset()
    h = _hist()
    svc = CheckerService(CASRegister(), capacity=128)
    ing = DeltaIngress(svc, port=0).start()
    try:
        body = (json.dumps({"key": "ik", "ops": [dict(o)
                                                 for o in h[:8]],
                            "wait": True, "timeout": 120})
                + "\n").encode()
        rq = urllib.request.Request(ing.url("/v1/deltas"), data=body)
        with urllib.request.urlopen(rq, timeout=120) as resp:
            out = json.loads(resp.read().decode().splitlines()[0])
        assert out.get("delta_id")
        spans = {s.sid: s for s in obs.tracer().spans()}
        req = [s for s in spans.values()
               if s.name == "serve.ingress.request"]
        assert req and req[0].args.get("delta_id") == out["delta_id"]
        admit = [s for s in spans.values() if s.name == "serve.admit"
                 and s.args.get("delta_id") == out["delta_id"]]
        assert admit
        # walk the admit span's ancestry to the ingress request span
        cur, seen = admit[0], set()
        while cur.parent is not None and cur.parent not in seen:
            seen.add(cur.parent)
            cur = spans.get(cur.parent)
            assert cur is not None, "parent id did not resolve"
            if cur.name == "serve.ingress.request":
                break
        assert cur.name == "serve.ingress.request", \
            [(s.name, s.sid, s.parent) for s in spans.values()]
    finally:
        ing.close()
        svc.close(drain=False)


# ------------------------------------------------ fleet trace merge


def _mini_doc(replica, epoch, sid_base=0, delta_id=None):
    args = {"span_id": sid_base + 1}
    if delta_id:
        args["delta_id"] = delta_id
    return {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "host"}},
        {"ph": "M", "pid": 1, "name": "trace_epoch",
         "args": {"unix": epoch}},
        {"ph": "X", "pid": 1, "tid": 7, "name": "serve.admit",
         "cat": "serve", "ts": 100.0, "dur": 5.0, "args": args},
    ], "trace": {"replica": replica, "epoch_unix": epoch}}


def test_merge_aligns_and_finds_cross_replica_chains():
    from jepsen_tpu.obs import trace_merge as tm
    a = _mini_doc("ra", 100.0, delta_id="xyz")
    b = _mini_doc("rb", 100.5, sid_base=10, delta_id="xyz")
    c = _mini_doc("rc", 101.0, sid_base=20, delta_id="only-c")
    merged = tm.merge_traces([a, b, c])
    assert tm.validate_trace(merged) == []
    assert merged["trace"]["aligned"] is True
    assert merged["trace"]["replicas"] == ["ra", "rb", "rc"]
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    by_rep = {e["args"]["replica"]: e for e in xs}
    # pids re-homed per replica; timestamps shifted by epoch offset
    assert by_rep["ra"]["pid"] != by_rep["rb"]["pid"]
    assert by_rep["ra"]["ts"] == 100.0
    assert by_rep["rb"]["ts"] == pytest.approx(100.0 + 0.5e6)
    assert by_rep["rc"]["ts"] == pytest.approx(100.0 + 1.0e6)
    # process tracks renamed per replica
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert "ra/host" in names and "rb/host" in names
    # the cross-replica chain query
    assert tm.cross_replica_ids(merged) == ["xyz"]
    # no trace_epoch events survive into the merged doc
    assert not any(e.get("name") == "trace_epoch"
                   for e in merged["traceEvents"])


def test_validator_catches_schema_violations():
    from jepsen_tpu.obs import trace_merge as tm
    doc = _mini_doc("ra", 100.0)
    assert tm.validate_trace(doc) == []
    bad = json.loads(json.dumps(doc))
    bad["traceEvents"][2]["args"].pop("span_id")
    bad["traceEvents"][2]["dur"] = -1
    bad["traceEvents"].append({"ph": "Z"})
    errs = tm.validate_trace(bad)
    assert len(errs) >= 3
    # dangling parent ids are violations too
    dangling = json.loads(json.dumps(doc))
    dangling["traceEvents"][2]["args"]["parent_id"] = 999
    assert any("parent_id" in e
               for e in tm.validate_trace(dangling))


def test_trace_endpoint_and_cli_merge(tmp_path, monkeypatch):
    """GET /trace on the ops endpoint exports the live span buffer;
    `jepsen trace` merges two exports and validates them (the
    fleet-merge path chaos drives over real subprocess replicas)."""
    import urllib.request
    from jepsen_tpu.obs import httpd as ops_httpd
    from jepsen_tpu.obs.trace_merge import trace_main
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    obs.reset()
    with obs.span("serve.admit", key="k", delta_id="tid-1"):
        pass
    ops = ops_httpd.start_ops_server(0, name="rep-a")
    try:
        with urllib.request.urlopen(ops.url("/trace"),
                                    timeout=10) as resp:
            doc = json.loads(resp.read().decode())
    finally:
        ops.close()
    assert doc["trace"]["replica"] == "rep-a" \
        and doc["trace"]["epoch_unix"] > 0
    assert any(e.get("args", {}).get("delta_id") == "tid-1"
               for e in doc["traceEvents"] if e["ph"] == "X")
    p1 = tmp_path / "a.trace.json"
    p2 = tmp_path / "b.trace.json"
    json.dump(doc, open(p1, "w"))
    doc2 = json.loads(json.dumps(doc))
    doc2["trace"]["replica"] = "rep-b"
    json.dump(doc2, open(p2, "w"))
    assert trace_main(["--validate", str(p1), str(p2)]) == 0
    out = tmp_path / "merged.json"
    assert trace_main([str(p1), str(p2), "--out", str(out)]) == 0
    merged = json.load(open(out))
    assert merged["trace"]["aligned"] is True
    from jepsen_tpu.obs.trace_merge import cross_replica_ids
    assert cross_replica_ids(merged) == ["tid-1"]
    # the CLI front door forwards pre-parse like lint/probe/status
    from jepsen_tpu.cli import main as cli_main
    assert cli_main(["trace", "--validate", str(out)]) == 0


def test_cli_merge_uniquifies_colliding_input_names(tmp_path):
    """Two scratch dirs each holding a 'trace.json' with NO embedded
    replica name must land on two DISTINCT process tracks — collapsing
    them onto one name would merge two span-id spaces (dangling
    parents could falsely resolve across replicas) and hide genuinely
    cross-replica chains."""
    from jepsen_tpu.obs.trace_merge import trace_main
    a = _mini_doc("ra", 100.0, delta_id="mig")
    b = _mini_doc("rb", 100.5, sid_base=10, delta_id="mig")
    for d, doc in (("d1", a), ("d2", b)):
        (tmp_path / d).mkdir()
        doc["trace"].pop("replica")        # path-derived name only
        json.dump(doc, open(tmp_path / d / "trace.json", "w"))
    out = tmp_path / "merged.json"
    assert trace_main(["--dir", str(tmp_path / "d1"),
                       "--dir", str(tmp_path / "d2"),
                       "--out", str(out)]) == 0
    merged = json.load(open(out))
    assert len(merged["trace"]["replicas"]) == 2
    assert len(set(merged["trace"]["replicas"])) == 2
    from jepsen_tpu.obs.trace_merge import cross_replica_ids
    assert cross_replica_ids(merged) == ["mig"]


def test_flight_dump_context_rides_the_flight_block(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "1")
    obs.reset()
    with obs.span("some.work"):
        pass
    path = obs.flight_dump("unit-test", dest_dir=str(tmp_path),
                           context={"delta_id": "d1", "key": "k1",
                                    "tenant": "t1"})
    doc = json.load(open(path))
    assert doc["flight"]["trigger"] == {"delta_id": "d1",
                                        "key": "k1", "tenant": "t1"}
    # context stays optional: no trigger block without one
    path2 = obs.flight_dump("unit-test-2", dest_dir=str(tmp_path))
    assert "trigger" not in json.load(open(path2))["flight"]
