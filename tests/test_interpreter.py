"""Interpreter tests — real threads, fake clients (reference:
jepsen/test/jepsen/generator/interpreter_test.clj)."""

import threading

import pytest

import jepsen_tpu.generator as gen
from jepsen_tpu.client import Client
from jepsen_tpu.generator import interpreter
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.util import reset_relative_time


class OkClient(Client):
    """Completes every op :ok instantly (interpreter_test.clj:18-24)."""

    def open(self, test, node):
        return OkClient()

    def invoke(self, test, op):
        o = Op(op)
        o["type"] = "ok"
        return o


class InfoNemesis(Nemesis):
    def invoke(self, test, op):
        o = Op(op)
        o["type"] = "info"
        return o


def base_test(**kw):
    reset_relative_time()
    t = {
        "concurrency": 4,
        "nodes": ["n1", "n2"],
        "client": OkClient(),
        "nemesis": InfoNemesis(),
    }
    t.update(kw)
    return t


def test_basic_run_structure():
    n = 100
    test = base_test(generator=gen.clients(
        gen.limit(n, lambda: {"f": "read"})))
    h = interpreter.run(test)
    invs = [o for o in h if o["type"] == "invoke"]
    oks = [o for o in h if o["type"] == "ok"]
    assert len(invs) == n
    assert len(oks) == n
    # histories pair up: every invoke has a later completion of the same
    # process
    seen = {}
    for o in h:
        p = o["process"]
        if o["type"] == "invoke":
            assert p not in seen
            seen[p] = o
        else:
            assert p in seen
            del seen[p]
    assert not seen


def test_times_monotonic():
    test = base_test(generator=gen.clients(
        gen.limit(50, lambda: {"f": "read"})))
    h = interpreter.run(test)
    times = [o["time"] for o in h]
    assert times == sorted(times)


def test_nemesis_routing():
    test = base_test(generator=gen.nemesis(
        gen.limit(3, lambda: {"f": "kill"})))
    h = interpreter.run(test)
    assert len(h) == 6
    assert all(o["process"] == "nemesis" for o in h)
    assert [o["type"] for o in h] == ["invoke", "info"] * 3


class CrashyClient(Client):
    """Every other invoke raises (interpreter_test.clj:145-177)."""

    counter = None  # shared across opens

    def __init__(self, counter=None):
        self.counter = counter

    def open(self, test, node):
        return CrashyClient(self.counter)

    def invoke(self, test, op):
        with self.counter["lock"]:
            self.counter["n"] += 1
            n = self.counter["n"]
        if n % 2 == 0:
            raise RuntimeError(f"crash {n}")
        o = Op(op)
        o["type"] = "ok"
        return o


def test_worker_crash_becomes_info_and_process_renumbered():
    counter = {"n": 0, "lock": threading.Lock()}
    test = base_test(
        client=CrashyClient(counter),
        generator=gen.clients(gen.limit(20, lambda: {"f": "w"})))
    h = interpreter.run(test)
    infos = [o for o in h if o["type"] == "info"]
    assert infos, "expected some crashes"
    for o in infos:
        assert o["error"].startswith("indeterminate: ")
    # a crashed process id never invokes again
    crashed = {o["process"] for o in infos}
    later_invokes = {}
    for i, o in enumerate(h):
        if o["type"] == "invoke":
            later_invokes.setdefault(o["process"], []).append(i)
    for p in crashed:
        info_idx = max(i for i, o in enumerate(h)
                       if o["process"] == p and o["type"] == "info")
        assert all(i < info_idx for i in later_invokes[p])


def test_log_and_sleep_excluded_from_history():
    test = base_test(generator=gen.clients(
        [gen.log("hello"), gen.sleep(0.01), gen.once({"f": "read"})]))
    h = interpreter.run(test)
    assert all(o.get("f") == "read" for o in h)
    assert len(h) == 2


def test_generator_exception_propagates():
    def boom(test, ctx):
        raise ValueError("generator boom")

    test = base_test(generator=gen.clients(boom))
    with pytest.raises(gen.GeneratorThrew):
        interpreter.run(test)


def test_throughput_floor():
    """The reference asserts >5,000 ops/s with 10 workers and a fake
    client (interpreter_test.clj:137-142; ~18,000 observed on the
    author's multi-core dev box). This build measures ~12,000 ops/s on a
    single-core CI box after the SimpleQueue scheduler path, so the
    reference's own floor holds here with ~2x headroom.

    Measured against time.process_time, not wall clock: this test was
    container-load-flaky — on a loaded (or 2-core) CI box, wall time
    inflates with co-tenant bursts while the interpreter's own work is
    unchanged, and the floor is a property of the interpreter, not of
    the neighbors (observed: wall-clock rate straddling the old floor
    at 3.9k-6k ops/s on an IDLE 2-core container). process_time counts
    CPU this process actually ran across ALL threads — dispatch loop
    AND the 10 workers — so a CPU-per-op regression on either side
    still trips it, while co-tenant load does not. (thread_time would
    be blind to the worker side: the main thread blocks in the
    completion queue while workers run the ops.) The floor derates
    from the reference's 5000 wall ops/s to 2000 ops per CPU-second:
    with 10 GIL-bound threads the summed CPU per op exceeds wall per
    op (~2.5-4.9k measured vs ~12k wall on an idle many-core box) —
    the derated CPU floor still catches any 2x CPU-per-op regression.
    Best of three shrugs off one-off outliers inside our own
    process."""
    import time
    n = 2000
    best = 0.0
    for _ in range(3):
        test = base_test(
            concurrency=10,
            generator=gen.clients(gen.limit(n, lambda: {"f": "r"})))
        t0 = time.process_time()
        h = interpreter.run(test)
        dt = max(time.process_time() - t0, 1e-9)
        assert len(h) == 2 * n
        best = max(best, n / dt)
        if best > 2000:
            break
    assert best > 2000, \
        f"throughput {best:.0f} ops/cpu-sec below the derated floor"
