"""Pipelined multi-key checking: parity, cache, and overlap tests.

The pipelined executor's contract is BIT-IDENTICAL results to serial
check_batch — verdicts, counterexample fields, engine/closure tags,
ordering — across every packable model family, plus a digest-keyed
encode cache whose invalidation is structural (content-keyed: mutate
a history and the key moves). These tests pin all of it on the 8-way
CPU mesh conftest provides.
"""

import os
import unittest.mock as mock

import numpy as np
import pytest

from jepsen_tpu import envflags
from jepsen_tpu.histories import (corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, info_op, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import engine
from jepsen_tpu.parallel import pipeline as pipe


def _h(*ops):
    return History.wrap(ops).index()


def _family_batches():
    """(model, histories) per packable family — clean + value-corrupted,
    mixed widths so both the bitdense and sparse tiers are exercised."""
    reg = [rand_register_history(n_ops=40, n_processes=3 + (s % 4),
                                 crash_p=0.05, fail_p=0.05, seed=s)
           for s in range(8)]
    reg[5] = corrupt_history(reg[5], seed=3, n_corruptions=2)
    gset = [rand_gset_history(n_ops=30, n_processes=4,
                              n_elements=5 if s % 2 else 12,
                              crash_p=0.06, seed=s + 70)
            for s in range(6)]
    uq = [rand_queue_history(n_ops=30, n_processes=4, n_values=3,
                             crash_p=0.06, seed=s + 80)
          for s in range(6)]
    fifo = [rand_fifo_history(n_ops=30, n_processes=5, n_values=3,
                              crash_p=0.15, seed=s + 90)
            for s in range(6)]
    mutex = [_h(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
                invoke_op(0, "release", None), ok_op(0, "release", None)),
             _h(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
                invoke_op(1, "acquire", None), ok_op(1, "acquire", None))]
    return [(CASRegister(), reg), (GSet(), gset), (UnorderedQueue(), uq),
            (FIFOQueue(), fifo), (Mutex(), mutex)]


# ------------------------------------------------------------- parity


@pytest.mark.parametrize("model,hs", _family_batches(),
                         ids=lambda v: type(v).__name__
                         if not isinstance(v, list) else "")
def test_pipeline_parity_all_families(model, hs):
    """Pipelined + cached results bit-identical to serial check_batch:
    same dicts (verdicts AND counterexample fields), same order, for
    clean and value-corrupted histories, across the bitdense and
    sparse dispatch tiers."""
    rs_serial = engine.check_batch(model, hs, capacity=64,
                                   max_capacity=4096)
    cache = pipe.EncodeCache(max_entries=64)
    rs_piped = engine.check_batch(model, hs, capacity=64,
                                  max_capacity=4096, pipeline=True,
                                  cache=cache)
    assert rs_piped == rs_serial
    # and again THROUGH the cache (every key a hit): still identical
    rs_cached = engine.check_batch(model, hs, capacity=64,
                                   max_capacity=4096, pipeline=True,
                                   cache=cache)
    assert rs_cached == rs_serial
    assert cache.counters()["hits"] == len(hs)


def test_pipeline_parity_small_chunks_and_depth():
    """Chunking must not leak into results: chunk_keys=2 (many chunks,
    deep streaming) matches the serial batch exactly, including an
    invalid key's counterexample fields."""
    model = CASRegister()
    hs = [rand_register_history(n_ops=40, n_processes=4, crash_p=0.04,
                                seed=500 + s) for s in range(9)]
    hs[4] = corrupt_history(hs[4], seed=3, n_corruptions=2)
    rs_serial = engine.check_batch(model, hs)
    rs = pipe.check_batch_pipelined(model, hs, cache=False,
                                    chunk_keys=2, depth=3)
    assert rs == rs_serial
    assert rs[4]["valid?"] is False and "op" in rs[4]


def test_pipeline_parity_exact_bucket_and_mesh():
    """bucket="exact" and a CPU mesh ride the pipelined path with the
    same results as serial; the env flag routes check_batch too."""
    import jax
    from jax.sharding import Mesh

    model = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=3 + (s % 3),
                                crash_p=0.03, seed=700 + s)
          for s in range(8)]
    mesh = Mesh(np.array(jax.devices()), ("keys",))
    rs_serial = engine.check_batch(model, hs, mesh=mesh, bucket="exact")
    rs_piped = engine.check_batch(model, hs, mesh=mesh, bucket="exact",
                                  pipeline=True, cache=False)
    assert rs_piped == rs_serial

    with mock.patch.dict(os.environ, {"JEPSEN_TPU_PIPELINE": "1"}):
        spied = {}
        real = pipe.check_batch_pipelined

        def spy(*a, **k):
            spied["called"] = True
            return real(*a, **k)

        with mock.patch.object(pipe, "check_batch_pipelined", spy):
            rs_env = engine.check_batch(model, hs[:3])
        assert spied.get("called"), "env flag did not route the pipeline"
        assert rs_env == rs_serial[:3]
    # malformed flag value fails loudly, never silently serial
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_PIPELINE": "yes"}), \
            pytest.raises(envflags.EnvFlagError,
                          match="JEPSEN_TPU_PIPELINE"):
        engine.check_batch(model, hs[:1])


def test_chunks_align_to_mesh():
    """With a mesh, every full chunk must be a multiple of the device
    count — place_batch only shards a divisible key axis, so an
    un-aligned chunk silently replicates every key to every device."""
    idxs = list(range(80))
    aligned = pipe._chunks(idxs, 32, align=8)
    assert [len(c) for c in aligned[:-1]] == [32, 32]
    assert all(len(c) % 8 == 0 for c in aligned[:-1])
    assert sum(aligned, []) == idxs
    # remainder chunk may be un-aligned (replicates, as serial would)
    assert [len(c) for c in pipe._chunks(list(range(20)), 32,
                                         align=8)] == [16, 4]
    # fewer keys than devices: one chunk, unavoidable replication
    assert [len(c) for c in pipe._chunks(list(range(5)), 32,
                                         align=8)] == [5]
    # chunk_keys below the device count floors at one aligned chunk
    assert [len(c) for c in pipe._chunks(list(range(16)), 4,
                                         align=8)] == [8, 8]
    # meshless near-equal split unchanged
    assert [len(c) for c in pipe._chunks(list(range(84)), 32)] \
        == [28, 28, 28]


def test_encode_cached_disabled_cache_short_circuit():
    """A disabled cache (max_entries=0) must not even pay the content
    digest: encode_cached goes straight to encode, no counters."""
    model = CASRegister()
    h = rand_register_history(n_ops=20, n_processes=3, seed=2)
    off = pipe.EncodeCache(max_entries=0)
    e = pipe.encode_cached(model, h, cache=off)
    assert engine.history_digest(e) == \
        engine.history_digest(enc_mod.encode(model, h))
    assert off.counters()["misses"] == 0    # never consulted
    assert off.counters()["encodes"] == 0


def test_pipeline_empty_batch():
    assert pipe.check_batch_pipelined(CASRegister(), []) == []
    with pytest.raises(ValueError, match="bucket"):
        pipe.check_batch_pipelined(CASRegister(), [], bucket="bogus")


def test_pipeline_via_independent_checker():
    """independent.checker(pipeline=True) threads the flag into the
    device batch path and keeps per-key results identical."""
    from jepsen_tpu import independent
    from jepsen_tpu.checker import linearizable

    model = CASRegister()
    ops = []
    for k in range(4):
        for s in range(6):
            ops.append(invoke_op(k, "write", independent.KV(k, s)))
            ops.append(ok_op(k, "write", independent.KV(k, s)))
    h = _h(*ops)
    base = independent.checker(linearizable(model, algorithm="jax"))
    piped = independent.checker(linearizable(model, algorithm="jax"),
                                pipeline=True)
    r1 = base.check({}, h)
    r2 = piped.check({}, h)
    assert r1 == r2
    assert r1["valid?"] is True
    assert all(v["analyzer"] == "jax" for v in r1["results"].values())


# ------------------------------------------------------- encode stages


def test_bulk_encode_matches_rowwise_all_families():
    """spec.encode_calls (the bulk fast path) must produce the same
    EncodedHistory as the row-wise encode_call loop — array-identical,
    pinned via history_digest (which also covers interning order)."""
    for model, hs in _family_batches():
        for h in hs:
            d_bulk = engine.history_digest(enc_mod.encode(model, h))
            d_loop = engine.history_digest(
                enc_mod.encode(model, h, use_bulk=False))
            assert d_bulk == d_loop, type(model).__name__


def test_prepare_finish_split_matches_encode():
    """finish_encode(prepare_encode(...)) is encode(...) exactly, and
    the stage-1 n_slots/n_states match what the pipeline buckets on."""
    model = CASRegister()
    h = rand_register_history(n_ops=60, n_processes=5, crash_p=0.06,
                              fail_p=0.06, seed=11)
    prep = enc_mod.prepare_encode(model, h)
    e2 = enc_mod.finish_encode(prep)
    e1 = enc_mod.encode(model, h)
    assert engine.history_digest(e1) == engine.history_digest(e2)
    assert prep.n_slots == e1.n_slots
    assert prep.n_states == e1.n_states


def test_encode_batch_rejects_pad_slots_with_encs():
    """encode_batch silently ignored pad_slots when pre-encoded encs
    were passed — now a loud conflict."""
    model = CASRegister()
    h = rand_register_history(n_ops=20, n_processes=3, seed=1)
    e = enc_mod.encode(model, h)
    with pytest.raises(ValueError, match="pad_slots"):
        engine.encode_batch(model, [], pad_slots=9, encs=[e])
    # each half alone still works
    encs, xs, state0 = engine.encode_batch(model, [], encs=[e])
    assert encs[0] is e
    encs2, _, _ = engine.encode_batch(model, [h], pad_slots=9)
    assert encs2[0].slot_f.shape[1] == 9


# --------------------------------------------------------------- cache


def test_cache_hit_zero_reencodes_and_mutation_guard():
    """Second pipelined run over the same histories: every key a cache
    hit, ZERO re-encodes, identical results. Then mutate one history
    in place: its digest moves, so the next run re-encodes exactly
    that key (no stale hit) and the verdict reflects the mutation —
    the cache-hit-after-mutation guard, keyed on history_digest."""
    model = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=3, crash_p=0.0,
                                fail_p=0.0, seed=900 + s)
          for s in range(5)]
    cache = pipe.EncodeCache(max_entries=32)
    st1 = {}
    rs1 = engine.check_batch(model, hs, pipeline=True, cache=cache,
                             pipeline_stats=st1)
    assert st1["cache"] == {"hits": 0, "disk_hits": 0, "misses": 5,
                            "encodes": 5, "entries": 5}
    st2 = {}
    rs2 = engine.check_batch(model, hs, pipeline=True, cache=cache,
                             pipeline_stats=st2)
    assert rs2 == rs1
    assert st2["cache"]["encodes"] == 0
    assert st2["cache"]["hits"] == 5

    # digest guard: the cached encoding IS the history's encoding
    key0 = pipe.encode_cache_key(model, hs[0])
    cached0 = cache.get(key0, model)
    assert engine.history_digest(cached0) == \
        engine.history_digest(enc_mod.encode(model, hs[0]))

    # in-place mutation: corrupt a read so the key becomes invalid
    old_digest = engine.history_digest(cached0)
    for o in hs[0]:
        if o.get("type") == "ok" and o.get("f") == "read":
            o["value"] = "never-written"
            break
    else:
        hs[0][-1]["value"] = "never-written"
    assert pipe.encode_cache_key(model, hs[0]) != key0
    st3 = {}
    rs3 = engine.check_batch(model, hs, pipeline=True, cache=cache,
                             pipeline_stats=st3)
    assert st3["cache"]["encodes"] == 1          # only the mutated key
    assert st3["cache"]["hits"] == 4
    assert rs3[0]["valid?"] is False, rs3[0]     # mutation observed
    assert rs3[1:] == rs1[1:]
    new_key = pipe.encode_cache_key(model, hs[0])
    assert engine.history_digest(cache.get(new_key, model)) != old_digest


def test_analysis_encode_cache_hook():
    """engine.analysis(encode_cache=...) re-analyzes the same history
    with zero re-encodes and the same result as the uncached path."""
    model = CASRegister()
    h = rand_register_history(n_ops=40, n_processes=4, crash_p=0.03,
                              seed=77)
    cache = pipe.EncodeCache(max_entries=8)
    r_plain = engine.analysis(model, h)
    r1 = engine.analysis(model, h, encode_cache=cache)
    c = cache.counters()
    assert c["encodes"] == 1 and c["misses"] == 1
    r2 = engine.analysis(model, h, encode_cache=cache)
    c = cache.counters()
    assert c["encodes"] == 1 and c["hits"] == 1   # no re-encode
    assert r1 == r2 == r_plain


def test_cache_lru_bound_and_disabled():
    model = CASRegister()
    hs = [rand_register_history(n_ops=20, n_processes=3, seed=s)
          for s in range(6)]
    cache = pipe.EncodeCache(max_entries=3)
    engine.check_batch(model, hs, pipeline=True, cache=cache)
    assert cache.counters()["entries"] == 3      # LRU bound held
    # capacity 0 disables: the pipelined path must not even pay the
    # content digests (no cache counters in stats), and nothing is
    # stored or counted on the disabled instance
    off = pipe.EncodeCache(max_entries=0)
    st = {}
    engine.check_batch(model, hs[:2], pipeline=True, cache=off,
                       pipeline_stats=st)
    assert "cache" not in st, st
    assert off.counters()["entries"] == 0
    assert off.counters()["misses"] == 0      # never even consulted
    # env-sized: malformed values raise at construction
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_ENCODE_CACHE": "16"}):
        assert pipe.EncodeCache().max_entries == 16
    with mock.patch.dict(os.environ,
                         {"JEPSEN_TPU_ENCODE_CACHE": "many"}), \
            pytest.raises(envflags.EnvFlagError,
                          match="JEPSEN_TPU_ENCODE_CACHE"):
        pipe.EncodeCache()
    with mock.patch.dict(os.environ,
                         {"JEPSEN_TPU_ENCODE_CACHE": "-1"}), \
            pytest.raises(envflags.EnvFlagError, match=">= 0"):
        pipe.EncodeCache()


def test_cache_refuses_to_persist_model_pruned_lane_entries(tmp_path):
    """A lane-family entry whose model-specific wildcard prune dropped
    calls AFTER spec.prepare (here: a crashed dequeue whose never-
    enqueued invoke value got a lane, then was pruned) must stay
    memory-only: a disk reload would rebuild prepare over the pruned
    call list and assign DIFFERENT lanes, so unpack_state on the
    rebuilt spec would decode wrong states."""
    model = UnorderedQueue()
    h = _h(invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
           invoke_op(1, "dequeue", "x"), info_op(1, "dequeue", "x"),
           invoke_op(2, "dequeue", None), ok_op(2, "dequeue", "a"))
    e = enc_mod.encode(model, h)
    assert e.model_pruned, "fixture must exercise the post-prepare prune"
    d = str(tmp_path / "c")
    c1 = pipe.EncodeCache(max_entries=8, store_dir=d)
    k = pipe.encode_cache_key(model, h)
    c1.put(k, e)
    assert c1.get(k, model) is e      # memory hit keeps the true spec
    assert os.listdir(d) == []        # never persisted
    c2 = pipe.EncodeCache(max_entries=8, store_dir=d)
    assert c2.get(k, model) is None   # fresh process: honest miss
    rs_serial = engine.check_batch(model, [h])
    rs = engine.check_batch(model, [h], pipeline=True, cache=c2)
    assert rs == rs_serial
    # an unpruned sibling still persists fine
    h2 = _h(invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
            invoke_op(1, "dequeue", None), ok_op(1, "dequeue", "a"))
    e2 = enc_mod.encode(model, h2)
    assert not e2.model_pruned
    c1.put(pipe.encode_cache_key(model, h2), e2)
    assert len(os.listdir(d)) == 1


def test_cache_byte_budget_evicts():
    """The LRU is byte-bounded, not just entry-bounded: large entries
    must not pin unbounded memory behind a generous entry count."""
    model = CASRegister()
    hs = [rand_register_history(n_ops=60, n_processes=6, crash_p=0.0,
                                seed=s) for s in range(4)]
    encs = [enc_mod.encode(model, h) for h in hs]
    budget = int(pipe.EncodeCache._entry_bytes(encs[0]) * 2)
    cache = pipe.EncodeCache(max_entries=100, max_bytes=budget)
    for h, e in zip(hs, encs):
        cache.put(pipe.encode_cache_key(model, h), e)
    c = cache.counters()
    assert c["entries"] < 4, c
    assert c["bytes"] <= budget, c
    # the newest entry always survives, even when it alone exceeds
    # the budget
    tiny = pipe.EncodeCache(max_entries=100, max_bytes=1)
    tiny.put(pipe.encode_cache_key(model, hs[0]), encs[0])
    assert tiny.counters()["entries"] == 1


def test_serial_path_rejects_pipeline_only_arguments():
    """cache / pipeline_stats on the serial path would be a silent
    no-op — check_batch raises instead (the encode_batch pad_slots
    rule, applied to this PR's own new arguments)."""
    model = CASRegister()
    h = rand_register_history(n_ops=20, n_processes=3, seed=1)
    with pytest.raises(ValueError, match="pipeline"):
        engine.check_batch(model, [h], cache=pipe.EncodeCache())
    with pytest.raises(ValueError, match="pipeline"):
        engine.check_batch(model, [h], pipeline_stats={})
    # cache=False means "no caching" — the serial path satisfies that
    # by doing nothing, so it must NOT crash env-flag-dependently
    rs_off = engine.check_batch(model, [h], cache=False)
    assert rs_off == engine.check_batch(model, [h])
    # with the pipeline on they are honored, not rejected
    st = {}
    rs = engine.check_batch(model, [h], pipeline=True, cache=False,
                            pipeline_stats=st)
    assert rs[0]["valid?"] in (True, False) and st["buckets"]


def test_cache_store_dir_persistence(tmp_path):
    """A fresh cache instance over the same store_dir serves every key
    from disk (zero re-encodes across 'processes'), with the prepared
    spec rebuilt — counterexample extraction still works on a loaded
    entry. A corrupt file degrades to a miss, not a crash."""
    model = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=3, crash_p=0.02,
                                seed=40 + s) for s in range(4)]
    hs[2] = corrupt_history(hs[2], seed=5, n_corruptions=2)
    d = str(tmp_path / "enc_cache")
    c1 = pipe.EncodeCache(max_entries=16, store_dir=d)
    rs1 = engine.check_batch(model, hs, pipeline=True, cache=c1)

    c2 = pipe.EncodeCache(max_entries=16, store_dir=d)
    st = {}
    rs2 = engine.check_batch(model, hs, pipeline=True, cache=c2,
                             pipeline_stats=st)
    assert rs2 == rs1
    assert st["cache"]["encodes"] == 0
    assert st["cache"]["disk_hits"] == len(hs)
    # a loaded entry's rebuilt spec unpacks states (history-dependent
    # packing path): check a gset roundtrip explicitly
    g = rand_gset_history(n_ops=24, n_processes=3, n_elements=4,
                          crash_p=0.0, seed=9)
    gc1 = pipe.EncodeCache(max_entries=4, store_dir=d)
    k = pipe.encode_cache_key(GSet(), g)
    gc1.put(k, enc_mod.encode(GSet(), g))
    gc2 = pipe.EncodeCache(max_entries=4, store_dir=d)
    loaded = gc2.get(k, GSet())
    assert loaded is not None and loaded.spec is not None
    assert loaded.spec.unpack_state(loaded.state0, loaded.intern) == GSet()

    # corruption: truncate one file -> miss, loud but non-fatal
    files = sorted(os.listdir(d))
    assert files
    with open(os.path.join(d, files[0]), "wb") as f:
        f.write(b"not a pickle")
    c3 = pipe.EncodeCache(max_entries=16, store_dir=d)
    rs3 = engine.check_batch(model, hs, pipeline=True, cache=c3)
    assert rs3 == rs1


# ------------------------------------------------- overlap / wall time


@pytest.mark.slow
def test_pipeline_84x120_cpu_overlap_and_cache_win():
    """The acceptance shape: 84 keys x 120 ops on the CPU mesh.
    (1) the double buffer genuinely streams (multiple chunks in
    flight, per-bucket encode/transfer/device split recorded);
    (2) results bit-identical to serial across serial/pipelined/
    cached runs; (3) the cache-warm pipelined end-to-end wall time is
    measurably below serial (zero re-encodes — on CPU the raw overlap
    is GIL-bound noise, the cache is the deterministic part of the
    win; on TPU the bench's pipelined line records the overlap win)."""
    from time import perf_counter

    model = CASRegister()
    # low concurrency keeps the CPU device phase comparable to encode
    # (n_processes=14 puts the batch in the C=16 tier, which a host
    # CPU cannot search in test time — BENCH_r03's fallback lesson)
    keys = [rand_register_history(n_ops=120, n_processes=4, n_values=5,
                                  crash_p=0.005, fail_p=0.05,
                                  seed=2024 + k) for k in range(84)]

    rs_serial = engine.check_batch(model, keys)          # warm compile
    cache = pipe.EncodeCache(max_entries=128)
    st_cold = {}
    rs_cold = engine.check_batch(model, keys, pipeline=True,
                                 cache=cache, pipeline_stats=st_cold)
    assert rs_cold == rs_serial
    # the stream really streamed: >1 chunk dispatched, split recorded
    assert sum(b["chunks"] for b in st_cold["buckets"]) >= 2, st_cold
    for b in st_cold["buckets"]:
        assert b["encode_secs"] > 0
        assert b["device_wait_secs"] >= 0

    serial_secs = min(_timed(lambda: engine.check_batch(model, keys))
                      for _ in range(3))
    best_cached = None
    for _ in range(3):
        st = {}
        dt = _timed(lambda: engine.check_batch(
            model, keys, pipeline=True, cache=cache, pipeline_stats=st))
        assert st["cache"]["encodes"] == 0, st["cache"]
        best_cached = dt if best_cached is None else min(best_cached, dt)
    assert best_cached < serial_secs, \
        (best_cached, serial_secs, st_cold["buckets"])
    # and the cached results are still the serial results
    rs_cached = engine.check_batch(model, keys, pipeline=True,
                                   cache=cache)
    assert rs_cached == rs_serial


def _timed(f):
    from time import perf_counter
    t0 = perf_counter()
    f()
    return perf_counter() - t0


def test_dispatch_finalize_matches_check_batch():
    """bitdense.dispatch_batch_bitdense + finalize is
    check_batch_bitdense exactly, and records the transfer/device
    timing split the pipeline and bench report."""
    from jepsen_tpu.parallel import bitdense

    model = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=3, crash_p=0.02,
                                seed=60 + s) for s in range(4)]
    hs[1] = corrupt_history(hs[1], seed=7, n_corruptions=2)
    encs = [enc_mod.encode(model, h) for h in hs]
    direct = bitdense.check_batch_bitdense(encs)
    pending = bitdense.dispatch_batch_bitdense(encs)
    rs = pending.finalize()
    assert rs == direct
    assert pending.transfer_secs >= 0
    assert pending.device_wait_secs >= 0
    assert pending.finalize() is rs              # idempotent
    # chunk floors: padding two keys to the 4-key batch's dims keeps
    # the same per-key results, and the R floor makes the chunk share
    # the bucket's program shape (one compile per bucket, not per
    # chunk)
    S_max = max(e.n_states for e in encs)
    C_max = max(5, max(e.n_slots for e in encs))
    R_max = max(e.n_returns for e in encs)
    pending2 = bitdense.dispatch_batch_bitdense(
        encs[:2], min_states=S_max, min_slots=C_max, min_returns=R_max)
    assert pending2.xs["ev_slot"].shape == (2, R_max)
    assert pending2.finalize() == direct[:2]
