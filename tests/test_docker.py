"""Docker harness validation — everything short of `compose up`.

No docker daemon exists in CI (VERDICT r3: "docker harness confidence
is YAML-only"), so this pins the next-best surface: compose-file
structure and cross-references after YAML anchor merging, the files the
configs point at, and bin/genkeys end-to-end. The actual `bin/up` on a
docker host is the one remaining manual step (docker/README.md).
Reference being paralleled: docker/bin/up:95-157 + docker-compose.yml.
"""

import os
import stat
import subprocess

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCKER = os.path.join(REPO, "docker")


def _load(name):
    with open(os.path.join(DOCKER, name)) as fh:
        return yaml.safe_load(fh)


def test_compose_base_structure():
    """The base file declares control + n1..n5 on one network, nodes
    from the shared anchor (tmpfs, authorized_keys mount, privileged),
    control depending on every node."""
    cfg = _load("docker-compose.yml")
    services = cfg["services"]
    assert set(services) == {"control", "n1", "n2", "n3", "n4", "n5"}
    assert set(cfg["networks"]) == {"jepsen"}

    control = services["control"]
    assert sorted(control["depends_on"]) == ["n1", "n2", "n3", "n4", "n5"]
    # control build context is the repo root with an in-tree dockerfile
    ctx = os.path.normpath(os.path.join(DOCKER, control["build"]["context"]))
    assert ctx == REPO
    assert os.path.exists(os.path.join(ctx, control["build"]["dockerfile"]))
    assert any("id_rsa" in v for v in control["volumes"])

    for n in ("n1", "n2", "n3", "n4", "n5"):
        node = services[n]
        # the x-node anchor must have merged: every node shares the
        # build context, privileged mode, and the authorized_keys mount
        assert node["build"] == "./node", n
        assert node["privileged"] is True, n
        assert node["hostname"] == n
        assert any("authorized_keys" in v for v in node["volumes"]), n
        assert node["networks"] == ["jepsen"], n
    assert os.path.exists(os.path.join(DOCKER, "node", "Dockerfile"))


def test_compose_overlays_reference_base_services():
    """Overlays may only touch services the base defines, and the
    ubuntu overlay's BASE_IMAGE arg must match an ARG in the node
    Dockerfile (the reference keeps a separate Dockerfile-ubuntu that
    can drift; the build-arg design is only safe while the arg
    exists)."""
    base = set(_load("docker-compose.yml")["services"])
    for overlay in ("docker-compose.dev.yml", "docker-compose.ubuntu.yml"):
        cfg = _load(overlay)
        assert set(cfg["services"]) <= base, overlay

    ubuntu = _load("docker-compose.ubuntu.yml")
    args = {a for s in ubuntu["services"].values()
            for a in s.get("build", {}).get("args", {})}
    assert args == {"BASE_IMAGE"}
    with open(os.path.join(DOCKER, "node", "Dockerfile")) as fh:
        df = fh.read()
    assert "ARG BASE_IMAGE" in df
    # the arg must be declared before FROM uses it
    assert df.index("ARG BASE_IMAGE") < df.index("FROM ${BASE_IMAGE}")


def test_compose_bind_mount_sources_are_generated_or_exist():
    """Every host-side bind-mount source must either exist in the tree
    or be produced by bin/genkeys (./secret/*) — a typo'd path would
    otherwise only surface as a cryptic error on the user's machine."""
    generated = {"./secret/id_rsa", "./secret/id_rsa.pub",
                 "./secret/authorized_keys"}
    for name in ("docker-compose.yml", "docker-compose.dev.yml"):
        for svc, spec in _load(name)["services"].items():
            for vol in spec.get("volumes", []):
                src = vol.split(":")[0]
                if not src.startswith(("./", "../")):
                    continue  # anonymous/variable volumes
                assert (src in generated
                        or os.path.exists(os.path.join(DOCKER, src))), \
                    (name, svc, src)


def test_genkeys_end_to_end(tmp_path):
    """bin/genkeys writes the keypair + authorized_keys with the right
    permissions, idempotently, into an alternate secret dir (so the
    repo's docker/secret is untouched)."""
    secret = tmp_path / "secret"
    r = subprocess.run([os.path.join(DOCKER, "bin", "genkeys"),
                        str(secret)],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    priv, pub = secret / "id_rsa", secret / "id_rsa.pub"
    auth = secret / "authorized_keys"
    for f in (priv, pub, auth):
        assert f.exists(), f
    assert auth.read_bytes() == pub.read_bytes()
    assert stat.S_IMODE(priv.stat().st_mode) == 0o600
    assert pub.read_text().startswith("ssh-rsa ")
    # private key parses and matches the public half (cryptography may
    # be absent on hosts where genkeys took the ssh-keygen path)
    serialization = pytest.importorskip(
        "cryptography.hazmat.primitives.serialization")
    key = serialization.load_pem_private_key(priv.read_bytes(), None)
    derived = key.public_key().public_bytes(
        serialization.Encoding.OpenSSH,
        serialization.PublicFormat.OpenSSH)
    assert pub.read_text().split()[:2] == derived.decode().split()[:2]

    # idempotent: a second run must not regenerate the key, and must
    # NOT clobber an authorized_keys the user has appended to
    before = priv.read_bytes()
    with open(auth, "a") as fh:
        fh.write("ssh-rsa AAAAexamplekey user@laptop\n")
    appended = auth.read_bytes()
    r2 = subprocess.run([os.path.join(DOCKER, "bin", "genkeys"),
                         str(secret)],
                        capture_output=True, text=True, timeout=60)
    assert r2.returncode == 0, r2.stderr
    assert priv.read_bytes() == before
    assert auth.read_bytes() == appended


def test_up_script_delegates_to_genkeys():
    """bin/up must route key generation through bin/genkeys (the
    CI-tested path) before handing off to docker compose."""
    with open(os.path.join(DOCKER, "bin", "up")) as fh:
        up = fh.read()
    assert "bin/genkeys" in up
    assert "docker compose up" in up
    assert "ssh-keygen" not in up  # no duplicated, untested keygen


@pytest.mark.skipif(
    subprocess.run(["sh", "-c", "command -v docker"],
                   capture_output=True).returncode != 0,
    reason="no docker daemon in this environment (manual step, "
           "docker/README.md)")
def test_compose_config_validates_with_docker():
    """On machines that do have docker: the real `compose config`
    validation, including both overlays."""
    for files in (["docker-compose.yml"],
                  ["docker-compose.yml", "docker-compose.dev.yml"],
                  ["docker-compose.yml", "docker-compose.ubuntu.yml"]):
        args = sum((["-f", f] for f in files), [])
        r = subprocess.run(["docker", "compose", *args, "config"],
                           cwd=DOCKER, capture_output=True, text=True,
                           env={**os.environ, "JEPSEN_ROOT": REPO})
        assert r.returncode == 0, (files, r.stderr)
