"""HTTP delta-ingress suite (ISSUE 12): streamed JSONL request/
response bodies over the asyncio stdlib server, bearer-token tenant
auth, structured sheds with tenant attribution, and the read
endpoints — all answering through the same admission layer as stdio.
"""

import json
import urllib.error
import urllib.request

import pytest

from jepsen_tpu.envflags import EnvFlagError
from jepsen_tpu.histories import rand_register_history
from jepsen_tpu.history import History
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, engine
from jepsen_tpu.serve import CheckerService, Tenant
from jepsen_tpu.serve import ingress as ingress_mod


def _post(url, body, token=None, timeout=120):
    headers = {}
    if token is not None:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(url, data=body.encode(),
                                 headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


def _lines(body):
    return [json.loads(ln) for ln in body.splitlines()]


def _history(seed=1):
    return list(rand_register_history(n_ops=16, n_processes=3,
                                      n_values=3, seed=seed))


def test_ingress_streamed_jsonl_end_to_end():
    """The whole grammar over one connection: submits (wait and not),
    an interleaved result, a finalize — responses stream back one
    JSONL line per request line, in order, and the final verdict
    matches the one-shot check."""
    h = _history()
    ref = engine.check_encoded(
        enc_mod.encode(CASRegister(), History.wrap(h)), capacity=128)
    svc = CheckerService(CASRegister(), capacity=128)
    with ingress_mod.DeltaIngress(svc, port=0) as ing:
        try:
            reqs = [
                {"key": "k", "ops": [dict(o) for o in h[:8]],
                 "wait": True, "timeout": 120},
                {"key": "k", "ops": [dict(o) for o in h[8:]],
                 "timeout": 60},
                {"op": "result", "key": "k", "timeout": 120},
                {"op": "finalize", "key": "k", "timeout": 120},
                {"bogus": 1},
                "not json at all",
            ]
            body = "\n".join(r if isinstance(r, str)
                             else json.dumps(r) for r in reqs) + "\n"
            code, text = _post(ing.url("/v1/deltas"), body)
            outs = _lines(text)
            assert code == 200 and len(outs) == 6
            assert outs[0]["valid?"] is not None and outs[0]["seq"] == 1
            assert outs[1]["accepted"] and outs[1]["seq"] == 2
            assert outs[2]["seq"] == 2
            assert outs[3]["valid?"] == ref["valid?"]
            assert "unknown request" in outs[4]["error"]
            assert "bad request line" in outs[5]["error"]
            # GET /v1/result answers the sealed verdict too
            with urllib.request.urlopen(
                    ing.url('/v1/result?key="k"'), timeout=60) as resp:
                r = json.loads(resp.read())
            assert r["valid?"] == ref["valid?"]
        finally:
            svc.close()


def test_ingress_auth_required_with_tenants():
    h = _history(seed=2)
    svc = CheckerService(
        CASRegister(), capacity=128,
        tenants=[Tenant("ia", token="tok-ia"),
                 Tenant("ib", token="tok-ib")])
    with ingress_mod.DeltaIngress(svc, port=0) as ing:
        try:
            delta = json.dumps({"key": "k", "ops": [dict(o)
                                                    for o in h[:8]],
                                "timeout": 60}) + "\n"
            # no token / unknown token -> 401 before the service runs
            for token in (None, "wrong"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post(ing.url("/v1/deltas"), delta, token=token)
                assert ei.value.code == 401
                assert "unauthorized" in json.loads(
                    ei.value.read())["error"]
            # the right token admits and the answer names the tenant
            code, text = _post(ing.url("/v1/deltas"), delta,
                               token="tok-ia")
            out = _lines(text)[0]
            assert out["accepted"] and out["tenant"] == "ia"
            # another tenant's token cannot read the key
            code, text = _post(ing.url("/v1/finalize"),
                               json.dumps({"key": "k",
                                           "timeout": 60}),
                               token="tok-ib")
            assert "another tenant" in json.loads(text)["error"]
        finally:
            svc.close()


def test_ingress_shed_carries_tenant_and_reason():
    h = _history(seed=3)
    svc = CheckerService(
        CASRegister(), capacity=128,
        tenants=[Tenant("iq", token="tq", max_pending_ops=8)],
        start_worker=False)
    with ingress_mod.DeltaIngress(svc, port=0) as ing:
        try:
            reqs = [{"key": "k", "ops": [dict(o) for o in h[:8]],
                     "timeout": 30},
                    {"key": "k", "ops": [dict(o) for o in h[8:16]],
                     "timeout": 30}]
            body = "".join(json.dumps(r) + "\n" for r in reqs)
            _code, text = _post(ing.url("/v1/deltas"), body,
                                token="tq", timeout=60)
            outs = _lines(text)
            assert outs[0]["accepted"]
            assert outs[1]["shed"] is True
            assert outs[1]["tenant"] == "iq"
            assert "pending-ops quota" in outs[1]["reason"]
        finally:
            svc.close(drain=False)


def test_ingress_unknown_endpoint_and_bad_key():
    svc = CheckerService(CASRegister(), capacity=128)
    with ingress_mod.DeltaIngress(svc, port=0) as ing:
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(ing.url("/nope"), timeout=30)
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    ing.url("/v1/result?key=notjson"), timeout=30)
            assert ei.value.code == 400
            with urllib.request.urlopen(ing.url("/"),
                                        timeout=30) as resp:
                doc = json.loads(resp.read())
            assert "/v1/deltas" in doc["endpoints"]
        finally:
            svc.close()


def test_ingress_port_flag_and_cli_parse(monkeypatch):
    from jepsen_tpu import cli
    args = cli.base_parser().parse_args(
        ["serve", "--checker", "--ingress-port", "0"])
    assert args.ingress_port == 0
    monkeypatch.delenv("JEPSEN_TPU_INGRESS_PORT", raising=False)
    assert ingress_mod.resolve_ingress_port(None) is None
    assert ingress_mod.resolve_ingress_port(8181) == 8181
    monkeypatch.setenv("JEPSEN_TPU_INGRESS_PORT", "7171")
    assert ingress_mod.resolve_ingress_port(None) == 7171
    monkeypatch.setenv("JEPSEN_TPU_INGRESS_PORT", "nope")
    with pytest.raises(EnvFlagError):
        ingress_mod.resolve_ingress_port(None)


def test_stdio_token_passthrough():
    """stdio is behind the same admission layer: a line's token
    resolves the tenant; with tenants configured and no token the
    request is refused."""
    from io import StringIO

    from jepsen_tpu.serve.stdio import run_stdio
    h = _history(seed=4)
    svc = CheckerService(CASRegister(), capacity=128,
                         tenants=[Tenant("st", token="ts")])
    reqs = [json.dumps({"key": "k", "ops": [dict(o) for o in h[:8]],
                        "token": "ts", "wait": True, "timeout": 120}),
            json.dumps({"key": "k", "ops": [dict(o) for o in h[8:]],
                        "timeout": 30}),   # no token -> refused
            json.dumps({"op": "result", "key": "k", "token": "ts",
                        "timeout": 60}),
            json.dumps({"op": "stop"})]
    out = StringIO()
    rc = run_stdio(svc, StringIO("\n".join(reqs) + "\n"), out)
    assert rc == 0
    lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert lines[0]["valid?"] is not None
    assert "tenant required" in lines[1]["error"]
    assert lines[2]["seq"] == 1


def test_ingress_bad_timeout_and_missing_content_length():
    """Review pins: a malformed query param answers 400 (never a
    dropped connection), and POST /v1/deltas without Content-Length
    answers 400 instead of silently acking nothing."""
    import http.client
    svc = CheckerService(CASRegister(), capacity=128)
    with ingress_mod.DeltaIngress(svc, port=0) as ing:
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    ing.url('/v1/result?key="k"&timeout=abc'),
                    timeout=30)
            assert ei.value.code == 400
            assert "timeout" in json.loads(ei.value.read())["error"]
            conn = http.client.HTTPConnection("127.0.0.1", ing.port,
                                              timeout=30)
            conn.putrequest("POST", "/v1/deltas",
                            skip_accept_encoding=True)
            conn.endheaders()   # no Content-Length, no body
            resp = conn.getresponse()
            assert resp.status == 400
            assert "Content-Length" in json.loads(
                resp.read())["error"]
            conn.close()
        finally:
            svc.close()
