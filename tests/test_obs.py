"""Tests for the unified telemetry subsystem (jepsen_tpu.obs).

Five contracts, per the observability PR's acceptance criteria:

1. disabled tracer = a true no-op: singleton context manager, a
   per-call CPU budget in the hundreds of nanoseconds, zero retained
   allocations inside the obs module on the hot path;
2. spans nest correctly ACROSS the pipeline's host worker-pool threads
   (contextvar propagation via ctx_runner);
3. the Chrome trace export is a valid trace-event array (loads as
   JSON, complete events carry ts/dur, metadata names the tracks);
4. the JSONL artifact round-trips through a store run dir;
5. checker results are BIT-IDENTICAL with tracing on vs off for all
   five packable model families (telemetry may never perturb
   verdicts).
"""

import json
import os
import threading
import tracemalloc
from time import process_time

import pytest

from jepsen_tpu import envflags, obs
from jepsen_tpu.histories import (corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    """Every test starts from flag-driven state and leaves nothing
    behind — tracing misconfigured here must not leak spans into the
    rest of the suite."""
    import jepsen_tpu.obs.export as export_mod

    monkeypatch.delenv("JEPSEN_TPU_TRACE", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_JAX_PROFILE", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_SEARCH_STATS", raising=False)
    obs.reset()
    obs.drain_search_stats()
    export_mod._last_reg_snapshot = {}
    yield
    obs.reset()
    obs.registry().reset()
    obs.drain_search_stats()
    export_mod._last_reg_snapshot = {}


def _h(*ops):
    return History.wrap(ops).index()


def _families():
    """(model, histories) per packable family — the test_pipeline
    parity set, shrunk: clean + one corrupted key each."""
    reg = [rand_register_history(n_ops=30, n_processes=4, crash_p=0.05,
                                 fail_p=0.05, seed=s) for s in range(4)]
    reg[2] = corrupt_history(reg[2], seed=3, n_corruptions=2)
    gset = [rand_gset_history(n_ops=24, n_processes=4, n_elements=5,
                              crash_p=0.06, seed=s + 70) for s in range(3)]
    uq = [rand_queue_history(n_ops=24, n_processes=4, n_values=3,
                             crash_p=0.06, seed=s + 80) for s in range(3)]
    fifo = [rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.15, seed=s + 90) for s in range(3)]
    mutex = [_h(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
                invoke_op(1, "acquire", None), ok_op(1, "acquire", None))]
    return [(CASRegister(), reg), (GSet(), gset), (UnorderedQueue(), uq),
            (FIFOQueue(), fifo), (Mutex(), mutex)]


# ------------------------------------------------- disabled = no-op


def test_disabled_span_is_singleton_noop():
    assert not obs.enabled()
    s1 = obs.span("a")
    s2 = obs.span("b", key=1)
    assert s1 is s2, "disabled span() must return the no-op singleton"
    with s1 as s:
        s.set(anything=True)       # absorbed, not stored
    assert s1.wall == 0.0 and s1.cpu == 0.0


def test_disabled_span_cpu_budget_and_zero_allocations():
    """The hot-path guard: with tracing off, span() must cost no more
    than a few hundred ns of CPU per call and retain NOTHING inside
    the obs module. Budgeted on process_time (load-insensitive, the
    test_interpreter throughput-floor precedent) with generous CI
    slack — a real Span construction (clock reads + contextvar + lock)
    costs microseconds and busts it."""
    N = 200_000
    for _ in range(1000):          # warm: resolve the env gate once
        obs.span("warm")
    c0 = process_time()
    for _ in range(N):
        with obs.span("hot"):
            pass
    cpu = process_time() - c0
    assert cpu / N < 2e-6, f"{cpu / N * 1e9:.0f}ns per disabled span"

    # zero retained allocations attributed to the obs package (the
    # package re-exports a `tracer` FUNCTION, which shadows the
    # submodule on attribute access — go through sys.modules)
    import sys
    trmod = sys.modules["jepsen_tpu.obs.tracer"]
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(50_000):
        with obs.span("hot"):
            pass
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    flt = (tracemalloc.Filter(True, trmod.__file__),)
    growth = sum(st.size_diff for st in
                 after.filter_traces(flt).compare_to(
                     before.filter_traces(flt), "filename"))
    assert growth <= 0, f"obs retained {growth} bytes over 50k no-ops"


def test_timer_measures_even_when_disabled():
    with obs.timer("t", shape="x") as tm:
        sum(range(50_000))
    assert tm.wall > 0
    assert obs.tracer() is None    # nothing recorded anywhere


# ------------------------------------------------- span mechanics


def test_span_nesting_and_timer_identity():
    tr = obs.configure(True)
    with obs.span("outer", a=1) as o:
        with obs.span("inner") as i:
            pass
    assert i.parent == o.sid and o.parent is None
    # timer's handle IS the recorded span: the emitted number and the
    # trace can never disagree
    with obs.timer("measured") as tm:
        pass
    assert tm in tr.spans()
    rec = [s for s in tr.spans() if s.name == "measured"][0]
    assert rec.t0 == tm.t0 and rec.t1 == tm.t1


def test_ctx_runner_propagates_across_threads():
    obs.configure(True)
    out = []
    with obs.span("root") as root:
        wrap = obs.ctx_runner()

        def work(k):
            with obs.span("child", key=k) as c:
                out.append(c)

        ts = [threading.Thread(target=wrap(work), args=(k,))
              for k in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
    assert len(out) == 4
    assert all(c.parent == root.sid for c in out)


def test_flag_gating_and_env_path_accessor(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "0")
    obs.reset()
    assert not obs.enabled()
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    obs.reset()
    assert obs.enabled() and obs.tracer().path == ""
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "/tmp/t.json")
    obs.reset()
    assert obs.tracer().path == "/tmp/t.json"
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "  ")
    obs.reset()
    with pytest.raises(envflags.EnvFlagError):
        obs.enabled()
    monkeypatch.setenv("JEPSEN_TPU_JAX_PROFILE", "1")
    assert obs.jax_profile_dir() == "store/jax_profile"
    monkeypatch.setenv("JEPSEN_TPU_JAX_PROFILE", "/tmp/prof")
    assert obs.jax_profile_dir() == "/tmp/prof"


# ------------------------------------------------- metrics registry


def test_registry_counter_gauge_histogram_and_delta():
    reg = obs.Registry()
    reg.counter("a.b").inc()
    reg.counter("a.b").inc(4)
    g = reg.gauge("depth")
    g.set(3)
    g.set(1)
    reg.histogram("secs").observe(0.5)
    reg.histogram("secs").observe(1.5)
    snap = reg.snapshot()
    assert snap["a.b"]["value"] == 5
    assert snap["depth"] == {"type": "gauge", "value": 1, "max": 3,
                             "nops": 2}
    assert snap["secs"]["count"] == 2 and snap["secs"]["mean"] == 1.0
    before = snap
    reg.counter("a.b").inc(2)
    d = reg.delta(before)
    assert d["a.b"]["value"] == 2 and "depth" not in d
    with pytest.raises(TypeError):
        reg.gauge("a.b")           # name/type collisions must raise


def test_delta_windows_gauges_and_histograms():
    """Per-window semantics: a gauge that MOVED but returned to its
    old level still shows up (with max None — its own peak stayed
    below the process high-water, so it is unknowable from
    snapshots); a window that raises the high-water reports it; a
    histogram window reports its own count/total/mean, with min/max
    only when every observation is the window's own."""
    reg = obs.Registry()
    g = reg.gauge("depth")
    g.inc(5), g.dec(5)                       # run 1 peaks at 5
    reg.histogram("secs").observe(2.0)
    before = reg.snapshot()
    d0 = reg.delta({})                       # first window vs empty
    assert d0["depth"] == {"type": "gauge", "value": 0, "max": 5,
                           "nops": 2}
    assert d0["secs"]["min"] == d0["secs"]["max"] == 2.0

    g.inc(1), g.dec(1)                       # run 2 peaks at 1 only
    reg.histogram("secs").observe(1.0)
    d = reg.delta(before)
    assert d["depth"] == {"type": "gauge", "value": 0, "max": None,
                          "nops": 2}
    secs = dict(d["secs"])
    # the bucket ladder subtracts window-correctly: this window owns
    # exactly its own 1.0s observation, cumulative from the 1.0 bound
    buckets = dict(secs.pop("buckets"))
    assert buckets[1.0] == 1 and buckets[0.25] == 0 and buckets[60.0] == 1
    assert secs == {"type": "histogram", "count": 1, "total": 1.0,
                    "min": None, "max": None, "mean": 1.0}

    g.inc(9), g.dec(9)                       # run 3 sets a new peak
    d = reg.delta(before)
    assert d["depth"]["max"] == 9
    assert reg.delta(reg.snapshot()) == {}   # quiet window: nothing


# ------------------------------------------------- pipeline nesting


def test_span_nesting_across_pipeline_worker_pool():
    """The acceptance nesting test: a pipelined multi-key run's
    prepare/encode spans (opened on pool threads) chain up to the
    pipeline.run root, and dispatch/finalize spans nest per chunk."""
    from jepsen_tpu.parallel import pipeline as pipe

    tr = obs.configure(True)
    model = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=4, crash_p=0.04,
                                seed=900 + s) for s in range(6)]
    pipe.check_batch_pipelined(model, hs, cache=False, chunk_keys=2,
                               depth=2)
    spans = {s.sid: s for s in tr.spans()}
    by_name = {}
    for s in spans.values():
        by_name.setdefault(s.name, []).append(s)

    assert len(by_name["pipeline.run"]) == 1
    root = by_name["pipeline.run"][0]
    assert len(by_name["pipeline.prepare"]) == len(hs)

    def ancestry(s):
        while s.parent is not None:
            s = spans[s.parent]
        return s

    for s in by_name["pipeline.prepare"] + by_name["pipeline.encode"]:
        assert ancestry(s) is root, (s.name, s.args)
    # the pool actually ran these off the main thread (the thing
    # contextvar propagation exists for)
    assert any(s.thread[1] != "MainThread"
               for s in by_name["pipeline.prepare"])
    # per-chunk dispatch/finalize pairs, nested under the root, plus
    # one synthetic device-track span per chunk
    n_chunks = len(by_name["pipeline.dispatch"])
    assert n_chunks >= 3            # 6 keys at chunk_keys=2
    assert len(by_name["pipeline.finalize"]) == n_chunks
    assert len(by_name["device.search"]) == n_chunks
    for s in by_name["pipeline.dispatch"]:
        assert spans[s.parent].name == "pipeline.run"
    assert all(s.track and s.track.startswith("bucket-")
               for s in by_name["device.search"])
    # the registry absorbed the executor's counters
    snap = obs.registry().snapshot()
    assert snap["pipeline.keys"]["value"] >= len(hs)
    assert snap["pipeline.chunks"]["value"] >= n_chunks
    assert snap["pipeline.inflight"]["max"] >= 1


# ------------------------------------------------- exporters


def _traced_run():
    from jepsen_tpu.parallel import engine

    model = CASRegister()
    hs = [rand_register_history(n_ops=24, n_processes=3, seed=s)
          for s in range(4)]
    engine.check_batch(model, hs, pipeline=True, cache=False)


def test_chrome_trace_schema(tmp_path):
    obs.configure(True)
    _traced_run()
    path = obs.write_chrome_trace(str(tmp_path / "trace.json"))
    with open(path) as fh:
        events = json.load(fh)     # valid JSON document
    assert isinstance(events, list) and events
    # "C" joined the set with the counter tracks (pipeline.inflight
    # samples ride every traced pipelined run)
    assert {e["ph"] for e in events} <= {"X", "M", "C"}
    cs = [e for e in events if e["ph"] == "C"]
    assert any(e["name"] == "pipeline.inflight" for e in cs), cs
    for e in cs:
        assert "value" in e["args"] and e["ts"] >= 0
    procs = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert procs == {"host", "device"}
    xs = [e for e in events if e["ph"] == "X"]
    assert xs
    for e in xs:
        assert isinstance(e["name"], str) and e["name"]
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert "pid" in e and "tid" in e
        assert "span_id" in e["args"]
    # device-bucket tracks exist and are named
    tracks = {e["args"]["name"] for e in events
              if e["ph"] == "M" and e["name"] == "thread_name"
              and e["pid"] == 2}
    assert any(t.startswith("bucket-") for t in tracks), tracks


def test_jsonl_store_dir_roundtrip(tmp_path):
    from jepsen_tpu import store as jstore

    obs.configure(True, path=str(tmp_path / "flag_trace.json"))
    obs.counter("engine.test_counter").inc(7)
    _traced_run()
    st = jstore.Store("obs-test", base_dir=str(tmp_path))
    arts = st.save_telemetry()
    assert arts is not None
    lines = [json.loads(ln) for ln in
             open(os.path.join(st.dir, "telemetry.jsonl"))]
    kinds = {ln["type"] for ln in lines}
    assert kinds == {"span", "metric"}
    names = {ln["name"] for ln in lines if ln["type"] == "span"}
    assert {"pipeline.run", "pipeline.prepare",
            "pipeline.dispatch"} <= names
    mets = {ln["name"]: ln for ln in lines if ln["type"] == "metric"}
    assert mets["engine.test_counter"]["value"] == 7
    # trace.json in the run dir AND at the flag path
    assert json.load(open(os.path.join(st.dir, "trace.json")))
    assert json.load(open(tmp_path / "flag_trace.json"))
    # the human summary mentions the hottest span names
    txt = open(os.path.join(st.dir, "telemetry.txt")).read()
    assert "pipeline.run" in txt and "engine.test_counter" in txt

    # a SECOND run in the same process must not overwrite the flag
    # path (the buffer was drained — the file would hold only run 2):
    # it gets a numbered sibling instead
    _traced_run()
    st2 = jstore.Store("obs-test", base_dir=str(tmp_path))
    arts2 = st2.save_telemetry()
    assert arts2["flag_trace"] == str(tmp_path / "flag_trace.2.json")
    assert json.load(open(tmp_path / "flag_trace.2.json"))
    assert json.load(open(tmp_path / "flag_trace.json"))  # run 1 intact


def test_env_flag_end_to_end_acceptance(tmp_path, monkeypatch):
    """The PR's acceptance criterion verbatim: with JEPSEN_TPU_TRACE=1
    (the env flag, not the programmatic gate), a multi-key pipelined
    check_batch run produces a valid Chrome trace whose encode/
    dispatch spans nest per key and per chunk, plus the JSONL artifact
    in a store run dir."""
    from jepsen_tpu import store as jstore
    from jepsen_tpu.parallel import engine

    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    obs.reset()
    model = CASRegister()
    hs = [rand_register_history(n_ops=24, n_processes=3, seed=s)
          for s in range(5)]
    engine.check_batch(model, hs, pipeline=True, cache=False,
                       pipeline_stats={})
    st = jstore.Store("obs-accept", base_dir=str(tmp_path))
    arts = st.save_telemetry()
    assert arts is not None
    events = json.load(open(os.path.join(st.dir, "trace.json")))
    xs = {e["name"]: e for e in events if e["ph"] == "X"}
    assert "pipeline.run" in xs and "pipeline.dispatch" in xs
    # per-key prepare spans, per-chunk dispatch spans
    keys = {e["args"].get("key") for e in events if e["ph"] == "X"
            and e["name"] == "pipeline.prepare"}
    assert keys == set(range(len(hs)))
    chunks = [e for e in events if e["ph"] == "X"
              and e["name"] == "pipeline.dispatch"]
    assert chunks and all("chunk" in e["args"] for e in chunks)
    # nesting: every dispatch's parent_id is the run span's id
    run_id = xs["pipeline.run"]["args"]["span_id"]
    assert all(e["args"]["parent_id"] == run_id for e in chunks)
    assert os.path.exists(os.path.join(st.dir, "telemetry.jsonl"))


def test_export_run_noop_when_disabled(tmp_path):
    assert obs.export_run(str(tmp_path)) is None
    assert not os.path.exists(tmp_path / "telemetry.jsonl")


def test_export_run_is_per_run(tmp_path):
    """A process that analyzes several runs (`--test-count`,
    test-all) must not leak run 1's spans or counter totals into run
    2's artifacts: export_run drains the span buffer and reports
    counters as deltas since the previous export."""
    tr = obs.configure(True)
    g = obs.gauge("pipeline.test_inflight")
    with obs.span("run.one"):
        pass
    obs.counter("engine.test_counter").inc(5)
    g.inc(5), g.dec(5)               # run 1 peaks at depth 5
    obs.histogram("engine.test_secs").observe(2.0)
    obs.export_run(str(tmp_path / "r1"))
    with obs.span("run.two"):
        pass
    obs.counter("engine.test_counter").inc(2)
    g.inc(1), g.dec(1)               # run 2 peaks at 1 — below run 1
    obs.histogram("engine.test_secs").observe(1.0)
    obs.export_run(str(tmp_path / "r2"))

    def load(d):
        return [json.loads(ln) for ln in
                open(os.path.join(str(tmp_path), d, "telemetry.jsonl"))]

    names1 = {ln["name"] for ln in load("r1") if ln["type"] == "span"}
    names2 = {ln["name"] for ln in load("r2") if ln["type"] == "span"}
    assert names1 == {"run.one"} and names2 == {"run.two"}

    def metric(d, name):
        m = [ln for ln in load(d) if ln["type"] == "metric"
             and ln["name"] == name]
        return m[0] if m else None

    assert metric("r2", "engine.test_counter")["value"] == 2  # not 7
    # the gauge MOVED in run 2, so it must not vanish from run 2's
    # artifacts just because it ended at the same level; run 1's
    # peak of 5 must not masquerade as run 2's (max: None = this
    # run's own peak stayed below the process high-water)
    assert metric("r1", "pipeline.test_inflight")["max"] == 5
    g2 = metric("r2", "pipeline.test_inflight")
    assert g2 is not None and g2["max"] is None
    # histograms report the run's own window, not cumulative totals
    h2 = metric("r2", "engine.test_secs")
    assert h2["count"] == 1 and h2["total"] == 1.0
    assert tr.spans() == []          # buffer drained, memory bounded


# ------------------------------------------------- parity on vs off


@pytest.mark.parametrize("model,hs", _families(),
                         ids=lambda v: type(v).__name__
                         if not isinstance(v, list) else "")
def test_results_bit_identical_tracing_on_vs_off(model, hs):
    """Telemetry may never perturb verdicts: serial and pipelined
    check_batch results are the same dicts with tracing off, on, and
    off again — for every packable family, clean + corrupted keys."""
    from jepsen_tpu.parallel import engine

    assert not obs.enabled()
    rs_off = engine.check_batch(model, hs, capacity=64,
                                max_capacity=4096)
    rs_off_p = engine.check_batch(model, hs, capacity=64,
                                  max_capacity=4096, pipeline=True,
                                  cache=False)
    obs.configure(True)
    rs_on = engine.check_batch(model, hs, capacity=64,
                               max_capacity=4096)
    rs_on_p = engine.check_batch(model, hs, capacity=64,
                                 max_capacity=4096, pipeline=True,
                                 cache=False)
    obs.reset()
    assert rs_on == rs_off
    assert rs_on_p == rs_off_p == rs_off


# ------------------------------------------------- engine counters


def test_engine_false_invalid_counter(monkeypatch):
    """The hoisted-logging satellite: the device-false-invalid
    override increments engine.false_invalid (routed through the
    registry, not just a log line)."""
    from jepsen_tpu.checker import wgl
    from jepsen_tpu.parallel import encode as enc_mod, engine

    e = enc_mod.encode(CASRegister(),
                       _h(invoke_op(0, "write", 1), ok_op(0, "write", 1),
                          invoke_op(1, "read", None), ok_op(1, "read", 1)))
    monkeypatch.setattr(wgl, "check_calls",
                        lambda *a, **k: {"valid?": True})
    obs.registry().reset()
    r = engine._disagreement_recheck(CASRegister(), e, "test note")
    assert r["valid?"] is True
    assert obs.registry().counter("engine.false_invalid").value == 1


def test_engine_capacity_escalation_counter():
    """check_encoded's overflow-doubling ladder is counted."""
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.parallel import encode as enc_mod, engine

    e = enc_mod.encode(CASRegister(), adversarial_register_history(
        n_ops=120, k_crashed=8, seed=7))
    obs.registry().reset()
    r = engine.check_encoded(e, capacity=64, max_capacity=1 << 16)
    assert r["valid?"] is True
    assert r["capacity"] > 64      # it did escalate
    esc = obs.registry().counter("engine.capacity_escalations").value
    assert esc >= 1
    assert obs.registry().counter("engine.configs_stepped").value \
        == r["configs-stepped"]
