"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip TPU hardware isn't available in CI; sharded code paths are
exercised on 8 virtual CPU devices instead (the driver separately
dry-runs the multi-chip path via __graft_entry__.dryrun_multichip).
Must run before the first `import jax` anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The env var alone is not enough in this image: the 'axon' TPU plugin
# re-registers itself regardless, so pin the platform via jax.config too.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache for the suite (same lever bench.py gives
# its children): the sharded shard_map programs cost 5-20s each to
# compile on the CPU backend, and re-runs of the suite re-pay every one
# of them. The cache lives in the repo tree (gitignored) so it survives
# across sessions on the same workspace; a fresh clone just runs cold.
jax.config.update(
    "jax_compilation_cache_dir",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
