"""Streaming checker parity + robustness suite (ISSUE 8 acceptance).

The load-bearing invariant: delta-fed verdicts are BIT-IDENTICAL to a
one-shot check of the same prefix — across the packable families,
both dedupe strategies, capacity growth, evict/thaw, kill-and-restart
WAL replay, duplicate deltas, and injected faults — and overload
degrades by backpressure/shedding with bounded memory, never by
dropping an admitted delta.
"""

import json
import os
import time
from io import StringIO

import numpy as np
import pytest

from jepsen_tpu import resilience
from jepsen_tpu.envflags import EnvFlagError
from jepsen_tpu.histories import (corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import encode as enc_mod, engine
from jepsen_tpu.parallel import extend as ext
from jepsen_tpu.serve import CheckerService, DeltaWAL

# Everything prefix-scan-determined must match the one-shot check:
# verdict, counterexample op + event, max-frontier, and the
# configs-stepped work counter (capacity/explored may differ — the
# session's ladder grows across deltas, the one-shot's from scratch).
PIN = ("valid?", "op", "fail-event", "max-frontier", "configs-stepped")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _oneshot(Model, ops, dedupe="sort", capacity=128):
    e = enc_mod.encode(Model(), History.wrap(list(ops)))
    return engine.check_encoded(e, capacity=capacity, dedupe=dedupe)


def _cuts(ops, n):
    step = -(-len(ops) // n)
    return [min(len(ops), (i + 1) * step) for i in range(n)]


FAMILIES = [
    ("cas-register", CASRegister,
     lambda: rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31)),
    ("gset", GSet,
     lambda: rand_gset_history(n_ops=36, n_processes=4, n_elements=9,
                               crash_p=0.06, seed=33)),
    ("uqueue", UnorderedQueue,
     lambda: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                crash_p=0.06, seed=34)),
    ("fifo", FIFOQueue,
     lambda: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                               crash_p=0.05, seed=35)),
]


# ------------------------------------------------- settling (host only)


def test_settled_events_certifies_prefix_rows():
    m = CASRegister()
    h = list(rand_register_history(n_ops=30, n_processes=4, seed=3))
    old = enc_mod.encode(m, History.wrap(h[:40]))
    new, settled = ext.extend_encoded(m, old, h[:40], h[40:])
    assert 0 <= settled <= old.n_returns
    # the certificate: rows below `settled` really are bit-identical
    for attr in ("slot_f", "slot_a0", "slot_a1", "slot_wild",
                 "slot_occ"):
        a = getattr(old, attr)[:settled]
        b = getattr(new, attr)[:settled, : old.slot_f.shape[1]]
        assert (a == b).all(), attr
    assert (old.ev_slot[:settled] == new.ev_slot[:settled]).all()
    # identical histories settle everything; a different model nothing
    again = enc_mod.encode(m, History.wrap(h[:40]))
    assert ext.settled_events(old, again) == old.n_returns
    assert ext.settled_events(None, new) == 0


def test_stable_events_bounds_open_calls():
    m = CASRegister()
    # p0's write stays open from the start: nothing before its first
    # return row may be treated as immutable
    h = History.wrap([
        invoke_op(0, "write", 1),
        invoke_op(1, "write", 2), ok_op(1, "write", 2),
        invoke_op(1, "read", None), ok_op(1, "read", 2),
    ])
    e = enc_mod.encode(m, h)
    assert ext.stable_events(list(h), e) == 0
    # fully completed stream: every row is immutable
    h2 = History.wrap([
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "read", None), ok_op(1, "read", 1),
    ])
    e2 = enc_mod.encode(m, h2)
    assert ext.stable_events(list(h2), e2) == e2.n_returns


# ------------------------------------------------------ session parity


@pytest.mark.parametrize("name,Model,gen", FAMILIES,
                         ids=[c[0] for c in FAMILIES])
def test_session_parity_families(name, Model, gen):
    """Delta-fed == one-shot, clean and corrupted, per family. Parity
    is checked at a mid-stream prefix AND the final one, so the
    resume-from-checkpoint path (not just the final answer) is
    pinned."""
    h = gen()
    for variant in (h, corrupt_history(h, seed=7, n_corruptions=2)):
        ops = list(variant)
        try:
            enc_mod.encode(Model(), History.wrap(ops))
        except enc_mod.EncodeError:
            continue   # family/shape not device-encodable: nothing to pin
        s = ext.HistorySession(Model(), capacity=128)
        cuts = _cuts(ops, 3)
        lo = 0
        for i, cut in enumerate(cuts):
            s.extend(ops[lo:cut])
            lo = cut
            r = s.check()
            if i in (1, len(cuts) - 1):
                assert _pin(r) == _pin(_oneshot(Model, ops[:cut])), \
                    (name, cut)
        assert r["stream"]["events"] == s.n_returns


def test_session_parity_hash_dedupe():
    h = list(rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31))
    s = ext.HistorySession(CASRegister(), capacity=128, dedupe="hash")
    lo = 0
    for cut in _cuts(h, 3):
        s.extend(h[lo:cut])
        lo = cut
        r = s.check()
    ref = _oneshot(CASRegister, h, dedupe="hash")
    assert _pin(r) == _pin(ref)
    assert r["dedupe"] == "hash"


def test_session_mutex_invalid_early_and_final():
    ops = [invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
           invoke_op(1, "acquire", None), ok_op(1, "acquire", None)]
    s = ext.HistorySession(Mutex(), capacity=64)
    s.extend(ops[:2])
    assert s.check()["valid?"] is True
    s.extend(ops[2:])
    r = s.check()
    ref = _oneshot(Mutex, ops, capacity=64)
    assert r["valid?"] is False
    assert _pin(r) == _pin(ref)
    # prefix closure: the invalid verdict is final — later deltas are
    # absorbed without a device re-scan and the verdict cannot flip
    s.extend([invoke_op(0, "release", None), ok_op(0, "release", None)])
    r2 = s.check()
    assert r2["valid?"] is False


def test_session_resumes_forward_not_from_scratch():
    h = list(rand_register_history(n_ops=60, n_processes=5, n_values=4,
                                   crash_p=0.03, seed=9))
    s = ext.HistorySession(CASRegister(), capacity=128)
    resumes = []
    lo = 0
    for cut in _cuts(h, 4):
        s.extend(h[lo:cut])
        lo = cut
        r = s.check()
        resumes.append(r["stream"]["resumed-from-event"])
    # later deltas must actually resume past the start: the settled
    # prefix is never re-searched
    assert resumes[0] == 0 and resumes[-1] > 0, resumes
    assert _pin(r) == _pin(_oneshot(CASRegister, h))


def test_session_capacity_growth_midstream():
    """A tiny initial capacity forces the overflow ladder ACROSS
    deltas; verdicts still match the roomy one-shot check."""
    h = list(rand_register_history(n_ops=50, n_processes=5, n_values=4,
                                   crash_p=0.05, fail_p=0.05, seed=11))
    s = ext.HistorySession(CASRegister(), capacity=64,
                           max_capacity=1 << 14)
    lo = 0
    for cut in _cuts(h, 3):
        s.extend(h[lo:cut])
        lo = cut
        r = s.check()
    ref = _oneshot(CASRegister, h, capacity=1024)
    assert r["valid?"] == ref["valid?"]
    assert r.get("op") == ref.get("op")
    assert r["max-frontier"] == ref["max-frontier"]


def test_session_finalize_extracts_paths_and_seals():
    h = list(corrupt_history(
        rand_register_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.05, seed=13),
        seed=2, n_corruptions=2))
    s = ext.HistorySession(CASRegister(), capacity=128)
    s.extend(h)
    r = s.finalize()
    if r["valid?"] is False:
        assert "final-paths" in r
    with pytest.raises(RuntimeError, match="finalized"):
        s.extend([invoke_op(0, "read", None)])


def test_session_rejects_malformed_delta_before_mutating():
    s = ext.HistorySession(CASRegister())
    with pytest.raises(ValueError, match="type"):
        s.extend([{"process": 0, "f": "read"}])
    assert s.n_ops == 0


# --------------------------------------------------- batched advance


def test_advance_sessions_batched_parity():
    m = CASRegister()
    streams = []
    for seed in range(3):
        h = rand_register_history(n_ops=30, n_processes=4, n_values=3,
                                  crash_p=0.05, seed=seed)
        if seed == 1:
            h = corrupt_history(h, seed=1, n_corruptions=2)
        streams.append(list(h))
    sessions = [ext.HistorySession(m, capacity=128, key=i)
                for i in range(3)]
    from jepsen_tpu import obs
    c0 = obs.registry().snapshot().get("stream.batched_keys",
                                       {}).get("value", 0)
    los = [0] * 3
    for frac in (0.5, 1.0):
        for i, s in enumerate(sessions):
            cut = int(len(streams[i]) * frac)
            s.extend(streams[i][los[i]:cut])
            los[i] = cut
        rs = ext.advance_sessions(sessions)
    c1 = obs.registry().snapshot()["stream.batched_keys"]["value"]
    assert c1 > c0   # the group really went through the batched scan
    for i, r in enumerate(rs):
        assert _pin(r) == _pin(_oneshot(CASRegister, streams[i])), i


# ------------------------------------------------------------ service


def _register_streams():
    h1 = list(rand_register_history(n_ops=24, n_processes=4,
                                    n_values=3, crash_p=0.05, seed=1))
    h2 = list(corrupt_history(
        rand_register_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.05, seed=2),
        seed=1, n_corruptions=2))
    return h1, h2


def test_service_stream_parity_drain_and_accounting(tmp_path):
    m = CASRegister()
    h1, h2 = _register_streams()
    svc = CheckerService(m, wal_dir=str(tmp_path / "wal"),
                         capacity=128, dedupe="sort")
    try:
        for a, b in ((0, 16), (16, 32), (32, 48)):
            for k, h in (("k1", h1), ("k2", h2)):
                r = svc.submit(k, h[a:b], wait=True, timeout=120)
                assert "valid?" in r, r
        f1 = svc.finalize("k1", timeout=120)
        f2 = svc.finalize("k2", timeout=120)
        assert svc.drain(timeout=60)
        # every admitted delta accounted for — no silent drops
        assert f1["seq"] == 3 and f2["seq"] == 3
        assert svc.stats()["pending_ops"] == 0
    finally:
        svc.close()
    assert _pin(f1) == _pin(_oneshot(CASRegister, h1))
    assert _pin(f2) == _pin(_oneshot(CASRegister, h2))
    assert f2["valid?"] is False and "final-paths" in f2


def test_service_duplicate_gap_and_finalized(tmp_path):
    m = CASRegister()
    h1, _ = _register_streams()
    svc = CheckerService(m, wal_dir=str(tmp_path / "wal"),
                         capacity=128)
    try:
        assert svc.submit("k", h1[:16], seq=1)["accepted"]
        dup = svc.submit("k", h1[:16], seq=1)
        assert dup["duplicate"] is True and dup["seq"] == 1
        gap = svc.submit("k", h1[16:32], seq=5)
        assert "sequence gap" in gap["error"]
        svc.finalize("k", timeout=120)
        sealed = svc.submit("k", h1[16:32])
        assert "finalized" in sealed["error"]
    finally:
        svc.close()


def test_service_restart_replays_wal_to_identical_verdicts(tmp_path):
    m = CASRegister()
    h1, h2 = _register_streams()
    wal = str(tmp_path / "wal")
    svc = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        for a, b in ((0, 24), (24, 48)):
            svc.submit("k1", h1[a:b], wait=True, timeout=120)
            svc.submit("k2", h2[a:b], wait=True, timeout=120)
        r1 = svc.result("k1", timeout=60)
        r2 = svc.result("k2", timeout=60)
    finally:
        svc.close()
    # kill-and-restart: replay must land bit-identical verdicts and
    # detect duplicate deltas by seq
    svc2 = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        q1 = svc2.result("k1", timeout=120)
        q2 = svc2.result("k2", timeout=120)
        assert _pin(q1) == _pin(r1) and q1["seq"] == r1["seq"]
        assert _pin(q2) == _pin(r2) and q2["seq"] == r2["seq"]
        assert svc2.submit("k1", h1[24:48], seq=2)["duplicate"]
    finally:
        svc2.close()


def test_service_wal_torn_tail_tolerated(tmp_path):
    m = CASRegister()
    h1, _ = _register_streams()
    wal = str(tmp_path / "wal")
    svc = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        svc.submit("k1", h1, wait=True, timeout=120)
        ref = svc.result("k1", timeout=60)
    finally:
        svc.close()
    # simulate a mid-write kill: a torn, unacknowledged trailing line
    fname = [n for n in os.listdir(wal) if n.endswith(".wal")][0]
    with open(os.path.join(wal, fname), "a") as fh:
        fh.write('{"seq": 2, "ops": ["trunc')
    svc2 = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        q = svc2.result("k1", timeout=120)
        assert _pin(q) == _pin(ref)
        assert q["seq"] == 1   # the torn delta was never admitted
    finally:
        svc2.close()


def test_service_backpressure_bounds_memory_and_sheds():
    """A producer outpacing the device: memory stays bounded (the
    global pending-ops bound), overload answers are structured
    ``{shed, reason}``, and every ACCEPTED delta is accounted for in
    the final verdict. The worker starts STOPPED so 'outpacing' is
    deterministic — nothing drains while the producer floods."""
    m = CASRegister()
    h = list(rand_register_history(n_ops=40, n_processes=4,
                                   n_values=3, seed=21))
    svc = CheckerService(m, capacity=128, per_key_queue=2,
                         global_bound=24, high_water=16,
                         start_worker=False)
    try:
        accepted = sheds = blocked = 0
        pieces = [h[i:i + 4] for i in range(0, len(h) - 3, 4)]
        for i, piece in enumerate(pieces):
            r = svc.submit(f"key-{i % 3}", piece, timeout=0.02)
            if r.get("accepted"):
                accepted += 1
            else:
                assert r.get("shed") is True and r.get("reason"), r
                sheds += 1
                if "queue full" in r["reason"]:
                    blocked += 1   # per-key backpressure, timed out
        assert sheds > 0, "overload never shed"
        assert svc.stats()["pending_ops"] <= 16   # shed held the line
        assert svc.stats()["max_pending_seen"] <= 24
        svc.start_worker()
        assert svc.drain(timeout=120)
        applied = sum(svc.result(f"key-{k}", timeout=60)["seq"]
                      for k in range(3))
        assert applied == accepted   # admitted != dropped, ever
        assert svc.stats()["pending_ops"] == 0
    finally:
        svc.close()


def test_wal_append_after_torn_tail_repairs_first(tmp_path):
    """A restart that APPENDS after a mid-write kill must truncate the
    torn trailing line first — otherwise the new record concatenates
    onto the partial bytes and an acknowledged delta becomes
    unparseable on the following restart."""
    ops = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    w = DeltaWAL(str(tmp_path))
    w.append("k", 1, ops)
    w.close()
    fname = [n for n in os.listdir(str(tmp_path))
             if n.endswith(".wal")][0]
    with open(os.path.join(str(tmp_path), fname), "a") as fh:
        fh.write('{"seq": 2, "ops": ["torn')   # no newline: mid-write
    w2 = DeltaWAL(str(tmp_path))
    w2.append("k", 2, ops)   # must repair, not concatenate
    w2.close()
    deltas = DeltaWAL(str(tmp_path)).replay("k")
    assert [s for s, _ in deltas] == [1, 2]


def test_service_concurrent_same_seq_submitters_one_wins():
    """Two producers racing the same explicit seq while the queue is
    full: exactly one is admitted, the other gets duplicate/gap after
    its wait — never two distinct deltas under one seq (which WAL
    replay would collapse, silently dropping an acknowledged one)."""
    import threading as th
    m = CASRegister()
    h = list(rand_register_history(n_ops=12, n_processes=3, seed=8))
    svc = CheckerService(m, capacity=128, per_key_queue=1,
                         start_worker=False)
    try:
        assert svc.submit("k", h[:4], seq=1)["accepted"]  # queue full
        outs = [None, None]

        def racer(i):
            outs[i] = svc.submit("k", h[4:8], seq=2, timeout=5)

        ts = [th.Thread(target=racer, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        time.sleep(0.2)
        svc.start_worker()   # drains the queue, releasing the waiters
        for t in ts:
            t.join(timeout=30)
        kinds = sorted("accepted" if o.get("accepted")
                       else "rejected" for o in outs)
        assert kinds == ["accepted", "rejected"], outs
        svc.drain(timeout=60)
        assert svc.result("k", timeout=30)["seq"] == 2
    finally:
        svc.close()


def test_service_worker_crash_without_wal_poisons_key(monkeypatch):
    """A worker crash that loses a key's in-memory state with NO WAL
    to rebuild from must poison the key — further deltas are refused
    — instead of silently restarting from a truncated history and
    serving a confident verdict over it."""
    from jepsen_tpu.serve import service as svc_mod
    m = CASRegister()
    h = list(rand_register_history(n_ops=12, n_processes=3, seed=6))
    svc = CheckerService(m, capacity=128)   # no wal_dir
    try:
        boom = lambda *a, **k: (_ for _ in ()).throw(  # noqa: E731
            RuntimeError("injected worker bug"))
        monkeypatch.setattr(svc_mod.ext, "advance_sessions", boom)
        r = svc.submit("k", h[:8], wait=True, timeout=60)
        assert r["valid?"] == "unknown" and "crashed" in r["error"]
        monkeypatch.undo()
        r2 = svc.submit("k", h[8:], timeout=5)
        assert "new key" in r2["error"], r2
    finally:
        svc.close()


def test_service_evict_thaw_midstream(tmp_path):
    m = CASRegister()
    h1, _ = _register_streams()
    ref = _oneshot(CASRegister, h1)
    svc = CheckerService(m, wal_dir=str(tmp_path / "wal"),
                         capacity=128, evict_idle_secs=0.1)
    try:
        svc.submit("k", h1[:24], wait=True, timeout=120)
        deadline = time.time() + 30
        while svc.stats()["keys_live"] > 0 and time.time() < deadline:
            time.sleep(0.02)
        assert svc.stats()["keys_live"] == 0, "idle key never evicted"
        cps = os.listdir(str(tmp_path / "wal" / "checkpoints"))
        assert any(n.endswith(".npz") for n in cps), cps
        # transparent thaw on the next delta, verdict unchanged
        r = svc.submit("k", h1[24:], wait=True, timeout=120)
        assert _pin(r) == _pin(ref)
    finally:
        svc.close()
    from jepsen_tpu import obs
    snap = obs.registry().snapshot()
    assert snap.get("serve.evictions", {}).get("value", 0) >= 1
    assert snap.get("serve.thaws", {}).get("value", 0) >= 1


def test_service_wedge_mid_stream_degrades_not_flips(monkeypatch):
    m = CASRegister()
    h1, _ = _register_streams()
    ref = _oneshot(CASRegister, h1)
    svc = CheckerService(m, capacity=128)
    try:
        svc.submit("k", h1[:24], wait=True, timeout=120)
        monkeypatch.setenv("JEPSEN_TPU_FAULTS", "wedge@search:n=4")
        resilience.reset()
        try:
            r = svc.submit("k", h1[24:], wait=True, timeout=120)
        finally:
            monkeypatch.delenv("JEPSEN_TPU_FAULTS")
            resilience.reset()
        # the streamed dispatch died: verdict preserved, degradation
        # structured (device-resume after the watchdog verdict, or
        # host resume from the checkpoint)
        assert r["valid?"] == ref["valid?"]
        assert r.get("resilience", {}).get("degraded") in (
            "device-resume", "host-resume", "host-wgl"), r
    finally:
        svc.close()


# --------------------------------------------- checkpoint meta compat


def test_frontier_checkpoint_meta_v1_v2_compat(tmp_path):
    """v1 (6 meta scalars) and v2 (7) checkpoint files keep loading —
    the streaming extension rides the v2 format and must not strand
    older files if it ever bumps the version."""
    cp = engine.FrontierCheckpoint(
        5, 64, "register", "cafebabecafebabe",
        np.arange(64, dtype=np.int32), np.zeros(64, np.uint32),
        np.zeros(64, np.uint32), np.arange(64) < 3, True, -1, 3, 7, 42)
    p2 = cp.save(str(tmp_path / "v2.npz"))
    l2 = engine.FrontierCheckpoint.load(p2)
    assert l2.stepped == 42 and l2.event_index == 5
    # rewrite as a v1 file: meta truncated to its 6 historical scalars
    z = np.load(p2, allow_pickle=False)
    np.savez_compressed(
        str(tmp_path / "v1.npz"), st=z["st"], ml=z["ml"], mh=z["mh"],
        live=z["live"], meta=z["meta"][:6], step_name=z["step_name"],
        history_digest=z["history_digest"])
    l1 = engine.FrontierCheckpoint.load(str(tmp_path / "v1.npz"))
    assert l1.stepped == 0 and l1.event_index == 5
    assert (l1.st == l2.st).all()


def test_encode_batch_accepts_matching_preallocated_widths():
    m = CASRegister()
    h = rand_register_history(n_ops=12, n_processes=3, seed=5)
    e9 = enc_mod.encode(m, h, pad_slots=9)
    # extension-style pre-padded encs at the requested width: legal
    _, xs, _ = engine.encode_batch(m, [], pad_slots=9, encs=[e9])
    assert xs["slot_f"].shape[-1] == 9
    # a mismatched width still fails loudly, pointing at the extension
    e = enc_mod.encode(m, h)
    with pytest.raises(ValueError, match="extension API"):
        engine.encode_batch(m, [], pad_slots=9, encs=[e])


# --------------------------------------------------- flags + transport


def test_serve_env_flags_validated(monkeypatch):
    from jepsen_tpu.serve import service as svc_mod
    monkeypatch.setenv("JEPSEN_TPU_SERVE_QUEUE", "banana")
    with pytest.raises(EnvFlagError):
        svc_mod._resolve_per_key_queue(None)
    monkeypatch.delenv("JEPSEN_TPU_SERVE_QUEUE")
    monkeypatch.setenv("JEPSEN_TPU_SERVE_GLOBAL", "0")
    with pytest.raises(EnvFlagError):
        svc_mod._resolve_global_bound(None)
    monkeypatch.delenv("JEPSEN_TPU_SERVE_GLOBAL")
    monkeypatch.setenv("JEPSEN_TPU_SERVE_EVICT_SECS", "-2")
    with pytest.raises(EnvFlagError):
        svc_mod._resolve_evict_secs(None)
    monkeypatch.delenv("JEPSEN_TPU_SERVE_EVICT_SECS")
    monkeypatch.setenv("JEPSEN_TPU_SERVE_WAL", " ")
    with pytest.raises(EnvFlagError):
        svc_mod.default_wal_dir()
    monkeypatch.setenv("JEPSEN_TPU_SERVE_WAL", "1")
    assert svc_mod.default_wal_dir().endswith("serve_wal")
    # defaults: high water sits below the hard bound
    monkeypatch.delenv("JEPSEN_TPU_SERVE_WAL")
    assert svc_mod._resolve_high_water(None, 100) == 75


def test_wal_roundtrip_and_duplicate_drop(tmp_path):
    w = DeltaWAL(str(tmp_path))
    ops = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    w.append(("reg", 7), 1, ops)
    w.append(("reg", 7), 2, ops)
    w.append(("reg", 7), 2, ops)   # duplicate line: replay drops it
    w.close()
    deltas = DeltaWAL(str(tmp_path)).replay(("reg", 7))
    assert [s for s, _ in deltas] == [1, 2]
    got = deltas[0][1]
    assert got[0]["type"] == "invoke" and got[0]["value"] == 1
    assert DeltaWAL(str(tmp_path)).keys() == [("reg", 7)]


def test_stdio_transport_roundtrip(tmp_path):
    from jepsen_tpu.serve.stdio import run_stdio
    m = CASRegister()
    h1, _ = _register_streams()
    reqs = [json.dumps({"key": "k", "ops": [dict(o) for o in h1[:24]],
                        "wait": True, "timeout": 120}),
            json.dumps({"key": "k", "ops": [dict(o) for o in h1[24:]],
                        "wait": True, "timeout": 120}),
            json.dumps({"op": "finalize", "key": "k", "timeout": 120}),
            json.dumps({"op": "stop"})]
    out = StringIO()
    svc = CheckerService(m, capacity=128)
    rc = run_stdio(svc, StringIO("\n".join(reqs) + "\n"), out)
    assert rc == 0
    lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert lines[-1] == {"stopped": True}
    final = lines[-2]
    ref = _oneshot(CASRegister, h1)
    assert final["valid?"] == ref["valid?"] and final["seq"] == 2


def test_cli_serve_checker_flags_parse():
    from jepsen_tpu import cli
    p = cli.base_parser()
    args = p.parse_args(["serve", "--checker", "--model", "fifo",
                         "--wal-dir", "/tmp/x", "--dedupe", "hash"])
    assert args.checker and args.model == "fifo"
    assert set(cli.SERVE_MODELS) >= {"cas-register", "gset", "fifo",
                                     "uqueue", "mutex", "register"}
