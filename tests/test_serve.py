"""Streaming checker parity + robustness suite (ISSUE 8 acceptance).

The load-bearing invariant: delta-fed verdicts are BIT-IDENTICAL to a
one-shot check of the same prefix — across the packable families,
both dedupe strategies, capacity growth, evict/thaw, kill-and-restart
WAL replay, duplicate deltas, and injected faults — and overload
degrades by backpressure/shedding with bounded memory, never by
dropping an admitted delta.
"""

import json
import os
import time
from io import StringIO

import numpy as np
import pytest

from jepsen_tpu import resilience
from jepsen_tpu.envflags import EnvFlagError
from jepsen_tpu.histories import (corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import encode as enc_mod, engine
from jepsen_tpu.parallel import extend as ext
from jepsen_tpu.serve import CheckerService, DeltaWAL

# Everything prefix-scan-determined must match the one-shot check:
# verdict, counterexample op + event, max-frontier, and the
# configs-stepped work counter (capacity/explored may differ — the
# session's ladder grows across deltas, the one-shot's from scratch).
PIN = ("valid?", "op", "fail-event", "max-frontier", "configs-stepped")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _oneshot(Model, ops, dedupe="sort", capacity=128):
    e = enc_mod.encode(Model(), History.wrap(list(ops)))
    return engine.check_encoded(e, capacity=capacity, dedupe=dedupe)


def _cuts(ops, n):
    step = -(-len(ops) // n)
    return [min(len(ops), (i + 1) * step) for i in range(n)]


FAMILIES = [
    ("cas-register", CASRegister,
     lambda: rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31)),
    ("gset", GSet,
     lambda: rand_gset_history(n_ops=36, n_processes=4, n_elements=9,
                               crash_p=0.06, seed=33)),
    ("uqueue", UnorderedQueue,
     lambda: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                crash_p=0.06, seed=34)),
    ("fifo", FIFOQueue,
     lambda: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                               crash_p=0.05, seed=35)),
]


# ------------------------------------------------- settling (host only)


def test_settled_events_certifies_prefix_rows():
    m = CASRegister()
    h = list(rand_register_history(n_ops=30, n_processes=4, seed=3))
    old = enc_mod.encode(m, History.wrap(h[:40]))
    new, settled = ext.extend_encoded(m, old, h[:40], h[40:])
    assert 0 <= settled <= old.n_returns
    # the certificate: rows below `settled` really are bit-identical
    for attr in ("slot_f", "slot_a0", "slot_a1", "slot_wild",
                 "slot_occ"):
        a = getattr(old, attr)[:settled]
        b = getattr(new, attr)[:settled, : old.slot_f.shape[1]]
        assert (a == b).all(), attr
    assert (old.ev_slot[:settled] == new.ev_slot[:settled]).all()
    # identical histories settle everything; a different model nothing
    again = enc_mod.encode(m, History.wrap(h[:40]))
    assert ext.settled_events(old, again) == old.n_returns
    assert ext.settled_events(None, new) == 0


def test_stable_events_bounds_open_calls():
    m = CASRegister()
    # p0's write stays open from the start: nothing before its first
    # return row may be treated as immutable
    h = History.wrap([
        invoke_op(0, "write", 1),
        invoke_op(1, "write", 2), ok_op(1, "write", 2),
        invoke_op(1, "read", None), ok_op(1, "read", 2),
    ])
    e = enc_mod.encode(m, h)
    assert ext.stable_events(list(h), e) == 0
    # fully completed stream: every row is immutable
    h2 = History.wrap([
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "read", None), ok_op(1, "read", 1),
    ])
    e2 = enc_mod.encode(m, h2)
    assert ext.stable_events(list(h2), e2) == e2.n_returns


# ------------------------------------------------------ session parity


@pytest.mark.parametrize("name,Model,gen", FAMILIES,
                         ids=[c[0] for c in FAMILIES])
def test_session_parity_families(name, Model, gen):
    """Delta-fed == one-shot, clean and corrupted, per family. Parity
    is checked at a mid-stream prefix AND the final one, so the
    resume-from-checkpoint path (not just the final answer) is
    pinned."""
    h = gen()
    for variant in (h, corrupt_history(h, seed=7, n_corruptions=2)):
        ops = list(variant)
        try:
            enc_mod.encode(Model(), History.wrap(ops))
        except enc_mod.EncodeError:
            continue   # family/shape not device-encodable: nothing to pin
        s = ext.HistorySession(Model(), capacity=128)
        cuts = _cuts(ops, 3)
        lo = 0
        for i, cut in enumerate(cuts):
            s.extend(ops[lo:cut])
            lo = cut
            r = s.check()
            if i in (1, len(cuts) - 1):
                assert _pin(r) == _pin(_oneshot(Model, ops[:cut])), \
                    (name, cut)
        assert r["stream"]["events"] == s.n_returns


def test_session_parity_hash_dedupe():
    h = list(rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31))
    s = ext.HistorySession(CASRegister(), capacity=128, dedupe="hash")
    lo = 0
    for cut in _cuts(h, 3):
        s.extend(h[lo:cut])
        lo = cut
        r = s.check()
    ref = _oneshot(CASRegister, h, dedupe="hash")
    assert _pin(r) == _pin(ref)
    assert r["dedupe"] == "hash"


def test_session_mutex_invalid_early_and_final():
    ops = [invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
           invoke_op(1, "acquire", None), ok_op(1, "acquire", None)]
    s = ext.HistorySession(Mutex(), capacity=64)
    s.extend(ops[:2])
    assert s.check()["valid?"] is True
    s.extend(ops[2:])
    r = s.check()
    ref = _oneshot(Mutex, ops, capacity=64)
    assert r["valid?"] is False
    assert _pin(r) == _pin(ref)
    # prefix closure: the invalid verdict is final — later deltas are
    # absorbed without a device re-scan and the verdict cannot flip
    s.extend([invoke_op(0, "release", None), ok_op(0, "release", None)])
    r2 = s.check()
    assert r2["valid?"] is False


def test_session_resumes_forward_not_from_scratch():
    h = list(rand_register_history(n_ops=60, n_processes=5, n_values=4,
                                   crash_p=0.03, seed=9))
    s = ext.HistorySession(CASRegister(), capacity=128)
    resumes = []
    lo = 0
    for cut in _cuts(h, 4):
        s.extend(h[lo:cut])
        lo = cut
        r = s.check()
        resumes.append(r["stream"]["resumed-from-event"])
    # later deltas must actually resume past the start: the settled
    # prefix is never re-searched
    assert resumes[0] == 0 and resumes[-1] > 0, resumes
    assert _pin(r) == _pin(_oneshot(CASRegister, h))


def test_session_capacity_growth_midstream():
    """A tiny initial capacity forces the overflow ladder ACROSS
    deltas; verdicts still match the roomy one-shot check."""
    h = list(rand_register_history(n_ops=50, n_processes=5, n_values=4,
                                   crash_p=0.05, fail_p=0.05, seed=11))
    s = ext.HistorySession(CASRegister(), capacity=64,
                           max_capacity=1 << 14)
    lo = 0
    for cut in _cuts(h, 3):
        s.extend(h[lo:cut])
        lo = cut
        r = s.check()
    ref = _oneshot(CASRegister, h, capacity=1024)
    assert r["valid?"] == ref["valid?"]
    assert r.get("op") == ref.get("op")
    assert r["max-frontier"] == ref["max-frontier"]


def test_session_finalize_extracts_paths_and_seals():
    h = list(corrupt_history(
        rand_register_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.05, seed=13),
        seed=2, n_corruptions=2))
    s = ext.HistorySession(CASRegister(), capacity=128)
    s.extend(h)
    r = s.finalize()
    if r["valid?"] is False:
        assert "final-paths" in r
    with pytest.raises(RuntimeError, match="finalized"):
        s.extend([invoke_op(0, "read", None)])


def test_session_rejects_malformed_delta_before_mutating():
    s = ext.HistorySession(CASRegister())
    with pytest.raises(ValueError, match="type"):
        s.extend([{"process": 0, "f": "read"}])
    assert s.n_ops == 0


# --------------------------------------------------- batched advance


def test_advance_sessions_batched_parity():
    m = CASRegister()
    streams = []
    for seed in range(3):
        h = rand_register_history(n_ops=30, n_processes=4, n_values=3,
                                  crash_p=0.05, seed=seed)
        if seed == 1:
            h = corrupt_history(h, seed=1, n_corruptions=2)
        streams.append(list(h))
    sessions = [ext.HistorySession(m, capacity=128, key=i)
                for i in range(3)]
    from jepsen_tpu import obs
    c0 = obs.registry().snapshot().get("stream.batched_keys",
                                       {}).get("value", 0)
    los = [0] * 3
    for frac in (0.5, 1.0):
        for i, s in enumerate(sessions):
            cut = int(len(streams[i]) * frac)
            s.extend(streams[i][los[i]:cut])
            los[i] = cut
        rs = ext.advance_sessions(sessions)
    c1 = obs.registry().snapshot()["stream.batched_keys"]["value"]
    assert c1 > c0   # the group really went through the batched scan
    for i, r in enumerate(rs):
        assert _pin(r) == _pin(_oneshot(CASRegister, streams[i])), i


# ------------------------------------------------------------ service


def _register_streams():
    h1 = list(rand_register_history(n_ops=24, n_processes=4,
                                    n_values=3, crash_p=0.05, seed=1))
    h2 = list(corrupt_history(
        rand_register_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.05, seed=2),
        seed=1, n_corruptions=2))
    return h1, h2


def test_service_stream_parity_drain_and_accounting(tmp_path):
    m = CASRegister()
    h1, h2 = _register_streams()
    svc = CheckerService(m, wal_dir=str(tmp_path / "wal"),
                         capacity=128, dedupe="sort")
    try:
        for a, b in ((0, 16), (16, 32), (32, 48)):
            for k, h in (("k1", h1), ("k2", h2)):
                r = svc.submit(k, h[a:b], wait=True, timeout=120)
                assert "valid?" in r, r
        f1 = svc.finalize("k1", timeout=120)
        f2 = svc.finalize("k2", timeout=120)
        assert svc.drain(timeout=60)
        # every admitted delta accounted for — no silent drops
        assert f1["seq"] == 3 and f2["seq"] == 3
        assert svc.stats()["pending_ops"] == 0
    finally:
        svc.close()
    assert _pin(f1) == _pin(_oneshot(CASRegister, h1))
    assert _pin(f2) == _pin(_oneshot(CASRegister, h2))
    assert f2["valid?"] is False and "final-paths" in f2


def test_service_duplicate_gap_and_finalized(tmp_path):
    m = CASRegister()
    h1, _ = _register_streams()
    svc = CheckerService(m, wal_dir=str(tmp_path / "wal"),
                         capacity=128)
    try:
        assert svc.submit("k", h1[:16], seq=1)["accepted"]
        dup = svc.submit("k", h1[:16], seq=1)
        assert dup["duplicate"] is True and dup["seq"] == 1
        gap = svc.submit("k", h1[16:32], seq=5)
        assert "sequence gap" in gap["error"]
        svc.finalize("k", timeout=120)
        sealed = svc.submit("k", h1[16:32])
        assert "finalized" in sealed["error"]
    finally:
        svc.close()


def test_service_restart_replays_wal_to_identical_verdicts(tmp_path):
    m = CASRegister()
    h1, h2 = _register_streams()
    wal = str(tmp_path / "wal")
    svc = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        for a, b in ((0, 24), (24, 48)):
            svc.submit("k1", h1[a:b], wait=True, timeout=120)
            svc.submit("k2", h2[a:b], wait=True, timeout=120)
        r1 = svc.result("k1", timeout=60)
        r2 = svc.result("k2", timeout=60)
    finally:
        svc.close()
    # kill-and-restart: replay must land bit-identical verdicts and
    # detect duplicate deltas by seq
    svc2 = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        q1 = svc2.result("k1", timeout=120)
        q2 = svc2.result("k2", timeout=120)
        assert _pin(q1) == _pin(r1) and q1["seq"] == r1["seq"]
        assert _pin(q2) == _pin(r2) and q2["seq"] == r2["seq"]
        assert svc2.submit("k1", h1[24:48], seq=2)["duplicate"]
    finally:
        svc2.close()


def test_service_wal_torn_tail_tolerated(tmp_path):
    m = CASRegister()
    h1, _ = _register_streams()
    wal = str(tmp_path / "wal")
    svc = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        svc.submit("k1", h1, wait=True, timeout=120)
        ref = svc.result("k1", timeout=60)
    finally:
        svc.close()
    # simulate a mid-write kill: a torn, unacknowledged trailing line
    fname = [n for n in os.listdir(wal) if n.endswith(".wal")][0]
    with open(os.path.join(wal, fname), "a") as fh:
        fh.write('{"seq": 2, "ops": ["trunc')
    svc2 = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        q = svc2.result("k1", timeout=120)
        assert _pin(q) == _pin(ref)
        assert q["seq"] == 1   # the torn delta was never admitted
    finally:
        svc2.close()


def test_service_backpressure_bounds_memory_and_sheds():
    """A producer outpacing the device: memory stays bounded (the
    global pending-ops bound), overload answers are structured
    ``{shed, reason}``, and every ACCEPTED delta is accounted for in
    the final verdict. The worker starts STOPPED so 'outpacing' is
    deterministic — nothing drains while the producer floods."""
    m = CASRegister()
    h = list(rand_register_history(n_ops=40, n_processes=4,
                                   n_values=3, seed=21))
    svc = CheckerService(m, capacity=128, per_key_queue=2,
                         global_bound=24, high_water=16,
                         start_worker=False)
    try:
        accepted = sheds = blocked = 0
        pieces = [h[i:i + 4] for i in range(0, len(h) - 3, 4)]
        for i, piece in enumerate(pieces):
            r = svc.submit(f"key-{i % 3}", piece, timeout=0.02)
            if r.get("accepted"):
                accepted += 1
            else:
                assert r.get("shed") is True and r.get("reason"), r
                sheds += 1
                if "queue full" in r["reason"]:
                    blocked += 1   # per-key backpressure, timed out
        assert sheds > 0, "overload never shed"
        assert svc.stats()["pending_ops"] <= 16   # shed held the line
        assert svc.stats()["max_pending_seen"] <= 24
        svc.start_worker()
        assert svc.drain(timeout=120)
        applied = sum(svc.result(f"key-{k}", timeout=60)["seq"]
                      for k in range(3))
        assert applied == accepted   # admitted != dropped, ever
        assert svc.stats()["pending_ops"] == 0
    finally:
        svc.close()


def test_wal_append_after_torn_tail_repairs_first(tmp_path):
    """A restart that APPENDS after a mid-write kill must truncate the
    torn trailing line first — otherwise the new record concatenates
    onto the partial bytes and an acknowledged delta becomes
    unparseable on the following restart."""
    ops = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    w = DeltaWAL(str(tmp_path))
    w.append("k", 1, ops)
    w.close()
    fname = [n for n in os.listdir(str(tmp_path))
             if n.endswith(".wal")][0]
    with open(os.path.join(str(tmp_path), fname), "a") as fh:
        fh.write('{"seq": 2, "ops": ["torn')   # no newline: mid-write
    w2 = DeltaWAL(str(tmp_path))
    w2.append("k", 2, ops)   # must repair, not concatenate
    w2.close()
    deltas = DeltaWAL(str(tmp_path)).replay("k")
    assert [s for s, _ in deltas] == [1, 2]


def test_service_concurrent_same_seq_submitters_one_wins():
    """Two producers racing the same explicit seq while the queue is
    full: exactly one is admitted, the other gets duplicate/gap after
    its wait — never two distinct deltas under one seq (which WAL
    replay would collapse, silently dropping an acknowledged one)."""
    import threading as th
    m = CASRegister()
    h = list(rand_register_history(n_ops=12, n_processes=3, seed=8))
    svc = CheckerService(m, capacity=128, per_key_queue=1,
                         start_worker=False)
    try:
        assert svc.submit("k", h[:4], seq=1)["accepted"]  # queue full
        outs = [None, None]

        def racer(i):
            outs[i] = svc.submit("k", h[4:8], seq=2, timeout=5)

        ts = [th.Thread(target=racer, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        time.sleep(0.2)
        svc.start_worker()   # drains the queue, releasing the waiters
        for t in ts:
            t.join(timeout=30)
        kinds = sorted("accepted" if o.get("accepted")
                       else "rejected" for o in outs)
        assert kinds == ["accepted", "rejected"], outs
        svc.drain(timeout=60)
        assert svc.result("k", timeout=30)["seq"] == 2
    finally:
        svc.close()


def test_service_worker_crash_without_wal_poisons_key(monkeypatch):
    """A worker crash that loses a key's in-memory state with NO WAL
    to rebuild from must poison the key — further deltas are refused
    — instead of silently restarting from a truncated history and
    serving a confident verdict over it."""
    from jepsen_tpu.serve import service as svc_mod
    m = CASRegister()
    h = list(rand_register_history(n_ops=12, n_processes=3, seed=6))
    svc = CheckerService(m, capacity=128)   # no wal_dir
    try:
        boom = lambda *a, **k: (_ for _ in ()).throw(  # noqa: E731
            RuntimeError("injected worker bug"))
        monkeypatch.setattr(svc_mod.ext, "advance_sessions", boom)
        r = svc.submit("k", h[:8], wait=True, timeout=60)
        assert r["valid?"] == "unknown" and "crashed" in r["error"]
        monkeypatch.undo()
        r2 = svc.submit("k", h[8:], timeout=5)
        assert "new key" in r2["error"], r2
    finally:
        svc.close()


def test_service_evict_thaw_midstream(tmp_path):
    m = CASRegister()
    h1, _ = _register_streams()
    ref = _oneshot(CASRegister, h1)
    svc = CheckerService(m, wal_dir=str(tmp_path / "wal"),
                         capacity=128, evict_idle_secs=0.1)
    try:
        svc.submit("k", h1[:24], wait=True, timeout=120)
        deadline = time.time() + 30
        while svc.stats()["keys_live"] > 0 and time.time() < deadline:
            time.sleep(0.02)
        assert svc.stats()["keys_live"] == 0, "idle key never evicted"
        cps = os.listdir(str(tmp_path / "wal" / "checkpoints"))
        assert any(n.endswith(".npz") for n in cps), cps
        # transparent thaw on the next delta, verdict unchanged
        r = svc.submit("k", h1[24:], wait=True, timeout=120)
        assert _pin(r) == _pin(ref)
    finally:
        svc.close()
    from jepsen_tpu import obs
    snap = obs.registry().snapshot()
    assert snap.get("serve.evictions", {}).get("value", 0) >= 1
    assert snap.get("serve.thaws", {}).get("value", 0) >= 1


def test_service_wedge_mid_stream_degrades_not_flips(monkeypatch):
    m = CASRegister()
    h1, _ = _register_streams()
    ref = _oneshot(CASRegister, h1)
    svc = CheckerService(m, capacity=128)
    try:
        svc.submit("k", h1[:24], wait=True, timeout=120)
        monkeypatch.setenv("JEPSEN_TPU_FAULTS", "wedge@search:n=4")
        resilience.reset()
        try:
            r = svc.submit("k", h1[24:], wait=True, timeout=120)
        finally:
            monkeypatch.delenv("JEPSEN_TPU_FAULTS")
            resilience.reset()
        # the streamed dispatch died: verdict preserved, degradation
        # structured (device-resume after the watchdog verdict, or
        # host resume from the checkpoint)
        assert r["valid?"] == ref["valid?"]
        assert r.get("resilience", {}).get("degraded") in (
            "device-resume", "host-resume", "host-wgl"), r
    finally:
        svc.close()


# --------------------------------------------- checkpoint meta compat


def test_frontier_checkpoint_meta_v1_v2_compat(tmp_path):
    """v1 (6 meta scalars) and v2 (7) checkpoint files keep loading —
    the streaming extension rides the v2 format and must not strand
    older files if it ever bumps the version."""
    cp = engine.FrontierCheckpoint(
        5, 64, "register", "cafebabecafebabe",
        np.arange(64, dtype=np.int32), np.zeros(64, np.uint32),
        np.zeros(64, np.uint32), np.arange(64) < 3, True, -1, 3, 7, 42)
    p2 = cp.save(str(tmp_path / "v2.npz"))
    l2 = engine.FrontierCheckpoint.load(p2)
    assert l2.stepped == 42 and l2.event_index == 5
    # rewrite as a v1 file: meta truncated to its 6 historical scalars
    z = np.load(p2, allow_pickle=False)
    np.savez_compressed(
        str(tmp_path / "v1.npz"), st=z["st"], ml=z["ml"], mh=z["mh"],
        live=z["live"], meta=z["meta"][:6], step_name=z["step_name"],
        history_digest=z["history_digest"])
    l1 = engine.FrontierCheckpoint.load(str(tmp_path / "v1.npz"))
    assert l1.stepped == 0 and l1.event_index == 5
    assert (l1.st == l2.st).all()


def test_encode_batch_accepts_matching_preallocated_widths():
    m = CASRegister()
    h = rand_register_history(n_ops=12, n_processes=3, seed=5)
    e9 = enc_mod.encode(m, h, pad_slots=9)
    # extension-style pre-padded encs at the requested width: legal
    _, xs, _ = engine.encode_batch(m, [], pad_slots=9, encs=[e9])
    assert xs["slot_f"].shape[-1] == 9
    # a mismatched width still fails loudly, pointing at the extension
    e = enc_mod.encode(m, h)
    with pytest.raises(ValueError, match="extension API"):
        engine.encode_batch(m, [], pad_slots=9, encs=[e])


# --------------------------------------------------- flags + transport


def test_serve_env_flags_validated(monkeypatch):
    from jepsen_tpu.serve import service as svc_mod
    monkeypatch.setenv("JEPSEN_TPU_SERVE_QUEUE", "banana")
    with pytest.raises(EnvFlagError):
        svc_mod._resolve_per_key_queue(None)
    monkeypatch.delenv("JEPSEN_TPU_SERVE_QUEUE")
    monkeypatch.setenv("JEPSEN_TPU_SERVE_GLOBAL", "0")
    with pytest.raises(EnvFlagError):
        svc_mod._resolve_global_bound(None)
    monkeypatch.delenv("JEPSEN_TPU_SERVE_GLOBAL")
    monkeypatch.setenv("JEPSEN_TPU_SERVE_EVICT_SECS", "-2")
    with pytest.raises(EnvFlagError):
        svc_mod._resolve_evict_secs(None)
    monkeypatch.delenv("JEPSEN_TPU_SERVE_EVICT_SECS")
    monkeypatch.setenv("JEPSEN_TPU_SERVE_WAL", " ")
    with pytest.raises(EnvFlagError):
        svc_mod.default_wal_dir()
    monkeypatch.setenv("JEPSEN_TPU_SERVE_WAL", "1")
    assert svc_mod.default_wal_dir().endswith("serve_wal")
    # defaults: high water sits below the hard bound
    monkeypatch.delenv("JEPSEN_TPU_SERVE_WAL")
    assert svc_mod._resolve_high_water(None, 100) == 75


def test_wal_roundtrip_and_duplicate_drop(tmp_path):
    w = DeltaWAL(str(tmp_path))
    ops = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    w.append(("reg", 7), 1, ops)
    w.append(("reg", 7), 2, ops)
    w.append(("reg", 7), 2, ops)   # duplicate line: replay drops it
    w.close()
    deltas = DeltaWAL(str(tmp_path)).replay(("reg", 7))
    assert [s for s, _ in deltas] == [1, 2]
    got = deltas[0][1]
    assert got[0]["type"] == "invoke" and got[0]["value"] == 1
    assert DeltaWAL(str(tmp_path)).keys() == [("reg", 7)]


def test_stdio_transport_roundtrip(tmp_path):
    from jepsen_tpu.serve.stdio import run_stdio
    m = CASRegister()
    h1, _ = _register_streams()
    reqs = [json.dumps({"key": "k", "ops": [dict(o) for o in h1[:24]],
                        "wait": True, "timeout": 120}),
            json.dumps({"key": "k", "ops": [dict(o) for o in h1[24:]],
                        "wait": True, "timeout": 120}),
            json.dumps({"op": "finalize", "key": "k", "timeout": 120}),
            json.dumps({"op": "stop"})]
    out = StringIO()
    svc = CheckerService(m, capacity=128)
    rc = run_stdio(svc, StringIO("\n".join(reqs) + "\n"), out)
    assert rc == 0
    lines = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert lines[-1] == {"stopped": True}
    final = lines[-2]
    ref = _oneshot(CASRegister, h1)
    assert final["valid?"] == ref["valid?"] and final["seq"] == 2


def test_cli_serve_checker_flags_parse():
    from jepsen_tpu import cli
    p = cli.base_parser()
    args = p.parse_args(["serve", "--checker", "--model", "fifo",
                         "--wal-dir", "/tmp/x", "--dedupe", "hash"])
    assert args.checker and args.model == "fifo"
    assert set(cli.SERVE_MODELS) >= {"cas-register", "gset", "fifo",
                                     "uqueue", "mutex", "register"}


# ----------------------------------------------- tenancy + fairness


def _tenant_svc(**kw):
    from jepsen_tpu.serve import Tenant
    m = CASRegister()
    tenants = kw.pop("tenants", None) or [
        Tenant("ten-a", token="tok-a", weight=1),
        Tenant("ten-b", token="tok-b", weight=1)]
    return CheckerService(m, capacity=128, tenants=tenants, **kw)


def test_tenant_spec_grammar_validated(monkeypatch):
    from jepsen_tpu.serve import TenantSpecError, parse_tenants, \
        resolve_tenants
    ts = parse_tenants("alice:token=aa:weight=3:ops=100:keys=2:wal=64,"
                       "bob:token=bb")
    assert ts[0].weight == 3 and ts[0].max_pending_ops == 100
    assert ts[0].max_keys == 2 and ts[0].max_wal_bytes == 64
    assert ts[1].weight == 1 and ts[1].token == "bb"
    for bad in ("al ice:token=t", "a:bogus=1", "a:weight=0",
                "a:ops=x", "a:token=", "a,a", "a:token=t,b:token=t"):
        with pytest.raises(TenantSpecError):
            parse_tenants(bad)
    # env resolution: unset -> None (single-tenant), malformed raises
    monkeypatch.delenv("JEPSEN_TPU_TENANTS", raising=False)
    assert resolve_tenants() is None
    monkeypatch.setenv("JEPSEN_TPU_TENANTS", "x:nope=1")
    with pytest.raises(EnvFlagError):
        resolve_tenants()
    monkeypatch.setenv("JEPSEN_TPU_TENANTS", "x:token=t:weight=2,y")
    tt = resolve_tenants()
    assert tt.names() == ["x", "y"] and tt.by_token("t").name == "x"
    # derived pending bound: weight share of the budget
    assert tt.pending_bound("x", 90) == 60
    assert tt.pending_bound("y", 90) == 30


def test_service_tenant_auth_and_isolation():
    h1, _ = _register_streams()
    svc = _tenant_svc()
    try:
        r = svc.submit("ka", h1[:8], token="tok-a")
        assert r["accepted"] and r["tenant"] == "ten-a"
        # unknown token / missing identity / wrong tenant name
        assert "unauthorized" in svc.submit("ka", h1[8:],
                                            token="zz")["error"]
        assert "tenant required" in svc.submit("ka", h1[8:])["error"]
        assert "unknown tenant" in svc.submit(
            "ka", h1[8:], tenant="nobody")["error"]
        # tenant isolation: ten-b cannot touch (or even probe) ka
        assert "another tenant" in svc.submit("ka", h1[8:],
                                              token="tok-b")["error"]
        assert "another tenant" in svc.result("ka",
                                              token="tok-b")["error"]
        assert "another tenant" in svc.finalize("ka",
                                                token="tok-b")["error"]
        # the owner still can; an UNIDENTIFIED read is refused too —
        # result/finalize are not a side door around the auth submit
        # enforces (a tokenless stdio line must not read, let alone
        # seal, another tenant's key)
        assert svc.result("ka", timeout=60,
                          token="tok-a").get("valid?") is not None
        assert "tenant required" in svc.result("ka")["error"]
        assert "tenant required" in svc.finalize("ka")["error"]
    finally:
        svc.close()


def test_service_tenant_quota_sheds_immediately():
    from jepsen_tpu.serve import Tenant
    h = list(rand_register_history(n_ops=40, n_processes=4,
                                   n_values=3, seed=22))
    svc = _tenant_svc(
        tenants=[Tenant("ten-q", token="tq", max_pending_ops=8,
                        max_keys=1)],
        start_worker=False)
    try:
        t0 = time.monotonic()
        assert svc.submit("q1", h[:8], token="tq")["accepted"]
        # pending-ops quota: IMMEDIATE shed (no backpressure wait),
        # structured reason + tenant
        r = svc.submit("q1", h[8:16], token="tq", timeout=30)
        assert r["shed"] is True and r["tenant"] == "ten-q"
        assert "pending-ops quota" in r["reason"]
        assert time.monotonic() - t0 < 5   # never sat out the timeout
        # key quota: a second key is refused before it is minted
        r2 = svc.submit("q2", h[:4], token="tq")
        assert r2["shed"] is True and "key quota" in r2["reason"]
        assert '"q2"' not in svc.status()["keys"]
        st = svc.status()["tenants"]["ten-q"]
        assert st["acct"]["sheds"] == 2 and st["pending_ops"] == 8
    finally:
        svc.close(drain=False)   # the worker never ran, by design


def test_service_tenant_wal_quota(tmp_path):
    from jepsen_tpu.serve import Tenant
    h = list(rand_register_history(n_ops=24, n_processes=3, seed=23))
    svc = _tenant_svc(
        tenants=[Tenant("ten-w", token="tw", max_wal_bytes=64)],
        wal_dir=str(tmp_path / "wal"))
    try:
        assert svc.submit("w1", h[:8], token="tw",
                          timeout=60)["accepted"]
        svc.drain(timeout=60)
        # the first delta's bytes blew the 64-byte quota: next sheds
        r = svc.submit("w1", h[8:16], token="tw", timeout=30)
        assert r["shed"] is True and "WAL-bytes quota" in r["reason"]
        assert svc.status()["tenants"]["ten-w"]["wal_bytes"] > 64
    finally:
        svc.close()


def test_tenant_fairness_flood_never_sheds_quiet_pin():
    """THE fairness acceptance pin: one tenant flooding past its
    quota, the other's deltas are NEVER shed, its ack p99 stays
    within SLO, and /metrics shows both per tenant."""
    from jepsen_tpu import obs
    from jepsen_tpu.obs import httpd as ops_httpd
    h = list(rand_register_history(n_ops=200, n_processes=4,
                                   n_values=3, seed=24))
    from jepsen_tpu.serve import Tenant
    svc = _tenant_svc(
        tenants=[Tenant("fp-flood", token="tf"),
                 Tenant("fp-quiet", token="tq2")],
        global_bound=200, high_water=100, start_worker=False)
    try:
        # flood: fp-flood's derived bound is 50 ops (weight share of
        # the high-water); everything past it sheds immediately
        flood_sheds = 0
        for i in range(0, 160, 4):
            r = svc.submit("fkey", h[i:i + 4], token="tf",
                           timeout=0.05)
            if r.get("shed"):
                flood_sheds += 1
                assert r["tenant"] == "fp-flood"
        assert flood_sheds > 0, "the flood never hit its quota"
        # quiet tenant: every delta admits, acks fast, zero sheds
        for i in range(0, 40, 4):
            r = svc.submit("qkey", h[i:i + 4], token="tq2",
                           timeout=5)
            assert r.get("accepted"), r
        st = svc.status()["tenants"]
        assert st["fp-quiet"]["acct"]["sheds"] == 0
        assert st["fp-flood"]["acct"]["sheds"] == flood_sheds
        # global queue never hit the shed line: the flood was capped
        # at ITS share, which is why the quiet tenant admits at all
        assert svc.stats()["pending_ops"] <= 100
        svc.start_worker()
        assert svc.drain(timeout=120)
        # nothing admitted was lost, per tenant
        assert svc.result("qkey", timeout=60,
                          token="tq2")["seq"] == 10
        # SLO: the quiet tenant's ack p99 from its LABELED histogram
        snap = obs.registry().snapshot()
        hq = snap[obs.labeled("serve.ack_secs", tenant="fp-quiet")]
        assert hq["count"] >= 10
        assert obs.hist_quantile(hq, 0.99) <= 2.5, hq
        # and the per-tenant series are visible on /metrics, labeled
        text = ops_httpd.render_prometheus()
        assert 'jepsen_serve_ack_secs_bucket{tenant="fp-quiet"' \
            in text
        assert 'jepsen_serve_sheds{tenant="fp-flood"}' in text
        parsed = ops_httpd.parse_prometheus(text)
        assert parsed[obs.labeled("jepsen_serve_ack_secs",
                                  tenant="fp-quiet")]["count"] >= 10
    finally:
        svc.close()


def test_tenant_drr_take_order_respects_weights():
    """White-box DRR pin: with equal backlogs, one worker cycle takes
    ops proportional to tenant weights (3:1 here), and leftover
    backlog stays queued for later cycles."""
    from jepsen_tpu.serve import Tenant
    h = list(rand_register_history(n_ops=96, n_processes=4, seed=25))
    svc = _tenant_svc(
        tenants=[Tenant("drr-big", token="b3", weight=3),
                 Tenant("drr-small", token="s1", weight=1)],
        global_bound=4096, high_water=0, drr_quantum=4,
        start_worker=False)
    try:
        for i in range(0, 48, 4):
            assert svc.submit("bk", h[i:i + 4], token="b3")["accepted"]
            assert svc.submit("sk", h[i:i + 4], token="s1")["accepted"]
        with svc._cond:
            batch = svc._take_work_locked()
        took = {ks.tenant: len(ops)
                for ks, ops, _seq, _f, _recs in batch}
        assert took == {"drr-big": 12, "drr-small": 4}
        # the rest stayed queued, accounted per tenant
        st = svc.status()["tenants"]
        assert st["drr-big"]["pending_ops"] == 36
        assert st["drr-small"]["pending_ops"] == 44
    finally:
        svc.close(drain=False)


def test_tenant_hammer_never_reorders_a_key(tmp_path):
    """Threaded multi-tenant hammer (the satellite pin): two tenants'
    producers interleave deltas on one service concurrently; every
    key's seq stream applies in order and the final verdicts are
    bit-identical to one-shot checks of each key's full stream."""
    import threading as th
    from jepsen_tpu.serve import Tenant
    streams = {}
    for i, key in enumerate(("h-a1", "h-a2", "h-b1", "h-b2")):
        streams[key] = list(rand_register_history(
            n_ops=20, n_processes=3, n_values=3, seed=40 + i))
    svc = _tenant_svc(
        tenants=[Tenant("hm-a", token="ha"), Tenant("hm-b",
                                                    token="hb")],
        wal_dir=str(tmp_path / "wal"), global_bound=4096,
        high_water=0)
    errs = []

    def producer(key, token):
        ops = streams[key]
        step = -(-len(ops) // 10)
        for seq in range(1, 11):
            lo = (seq - 1) * step
            r = svc.submit(key, ops[lo:lo + step], seq=seq,
                           token=token, timeout=120)
            if not r.get("accepted"):
                errs.append((key, seq, r))
                return

    try:
        threads = [th.Thread(target=producer, args=(k, t))
                   for k, t in (("h-a1", "ha"), ("h-a2", "ha"),
                                ("h-b1", "hb"), ("h-b2", "hb"))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not errs, errs
        assert svc.drain(timeout=120)
        for key, ops in streams.items():
            r = svc.result(key, timeout=60,
                           token="ha" if "-a" in key else "hb")
            assert r["seq"] == 10, (key, r)
            assert _pin(r) == _pin(_oneshot(CASRegister, ops)), key
    finally:
        svc.close()


def test_service_tenant_recovery_rehomes_ownership(tmp_path):
    """Kill-and-restart keeps tenancy: the WAL header's tenant stamp
    re-homes each key to its owner, WAL-bytes accounting is restored,
    and cross-tenant access stays refused after the restart."""
    from jepsen_tpu.serve import Tenant
    h1, _ = _register_streams()
    wal = str(tmp_path / "wal")
    tenants = [Tenant("rc-a", token="ra"), Tenant("rc-b", token="rb")]
    svc = _tenant_svc(tenants=list(tenants), wal_dir=wal)
    try:
        svc.submit("rka", h1[:24], token="ra", wait=True, timeout=120)
        ref = svc.result("rka", timeout=60, token="ra")
    finally:
        svc.close()
    svc2 = _tenant_svc(tenants=list(tenants), wal_dir=wal)
    try:
        st = svc2.status()
        assert st["keys"]['"rka"']["tenant"] == "rc-a"
        assert st["tenants"]["rc-a"]["wal_bytes"] > 0
        assert "another tenant" in svc2.submit(
            "rka", h1[24:], token="rb")["error"]
        q = svc2.result("rka", timeout=120, token="ra")
        assert _pin(q) == _pin(ref)
    finally:
        svc2.close()


# ------------------------------------------------- WAL segmentation


def test_wal_rotate_segments_replay_and_sizes(tmp_path):
    ops = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    w = DeltaWAL(str(tmp_path))
    w.append("k", 1, ops)
    w.rotate("k")
    w.append("k", 2, ops)
    w.append("k", 3, ops)
    w.close()
    w2 = DeltaWAL(str(tmp_path))
    segs = w2.segments("k")
    assert len(segs) == 2 and segs[0].endswith(".wal") \
        and segs[1].endswith(".wal.1")
    assert [s for s, _ in w2.replay("k")] == [1, 2, 3]
    assert w2.keys() == ["k"]
    assert w2.size_bytes("k") == sum(os.path.getsize(p) for p in segs)
    # appends continue into the newest segment, never a sealed one
    w2.append("k", 4, ops)
    assert [s for s, _ in w2.replay("k")] == [1, 2, 3, 4]
    assert os.path.getsize(segs[0]) == w2.size_bytes("k") \
        - os.path.getsize(segs[1])
    w2.close()
    # rotating a never-written key is a no-op, not an orphaned file
    w3 = DeltaWAL(str(tmp_path / "fresh"))
    w3.rotate("nope")
    w3.append("nope", 1, ops)
    assert len(w3.segments("nope")) == 1
    w3.close()


def test_wal_auto_rotation_by_size(tmp_path, monkeypatch):
    ops = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    w = DeltaWAL(str(tmp_path), segment_bytes=150)
    for seq in range(1, 6):
        w.append("k", seq, ops)
    w.close()
    assert len(DeltaWAL(str(tmp_path)).segments("k")) >= 2
    assert [s for s, _ in DeltaWAL(str(tmp_path)).replay("k")] \
        == [1, 2, 3, 4, 5]
    # the env knob is validated like every other flag
    monkeypatch.setenv("JEPSEN_TPU_SERVE_WAL_SEGMENT_BYTES", "nope")
    with pytest.raises(EnvFlagError):
        DeltaWAL(str(tmp_path / "x"))
    monkeypatch.setenv("JEPSEN_TPU_SERVE_WAL_SEGMENT_BYTES", "-1")
    with pytest.raises(EnvFlagError):
        DeltaWAL(str(tmp_path / "x"))


def test_wal_torn_tail_tolerated_across_segment_boundary(tmp_path):
    """The re-pinned torn-tail contract: a torn trailing line in a
    NON-final segment (crash mid-write, restart rotated) is an
    unacknowledged kill — tolerated and counted — while a corrupt
    line BEFORE any segment's tail stays a loud WALError."""
    import json as _json
    ops = [invoke_op(0, "write", 1), ok_op(0, "write", 1)]
    w = DeltaWAL(str(tmp_path))
    w.append("k", 1, ops)
    w.close()
    base = DeltaWAL(str(tmp_path)).segments("k")[0]
    with open(base, "a") as fh:
        fh.write('{"seq": 2, "ops": ["torn')   # mid-write kill
    # the restart rotated before appending: segment 1 exists with its
    # own header + an acknowledged delta
    with open(base + ".1", "w") as fh:
        fh.write(_json.dumps({"key": '"k"', "segment": 1}) + "\n")
        from jepsen_tpu.history import op_to_edn_str
        fh.write(_json.dumps(
            {"seq": 3, "ops": [op_to_edn_str(o) for o in ops]}) + "\n")
    deltas = DeltaWAL(str(tmp_path)).replay("k")
    assert [s for s, _ in deltas] == [1, 3]
    # but corruption BEFORE a segment's tail is acknowledged data
    with open(base + ".1", "a") as fh:
        fh.write(_json.dumps(
            {"seq": 4, "ops": [op_to_edn_str(o) for o in ops]}) + "\n")
    lines = open(base + ".1").read().splitlines()
    lines[1] = '{"seq": 3, "ops": ["broken'
    with open(base + ".1", "w") as fh:
        fh.write("\n".join(lines) + "\n")
    from jepsen_tpu.serve import WALError
    with pytest.raises(WALError, match="not the tail"):
        DeltaWAL(str(tmp_path)).replay("k")


def test_tenant_drr_finalize_waits_for_queue_drain():
    """Review pin: a finalize requested while the tenant's deficit
    ran out mid-drain must NOT seal the key over acknowledged-but-
    unapplied deltas — the final verdict covers every admitted delta
    (bit-identical to one-shot), however many DRR cycles that takes."""
    import threading as th
    from jepsen_tpu.serve import Tenant
    h = list(rand_register_history(n_ops=48, n_processes=4, seed=26))
    svc = _tenant_svc(
        tenants=[Tenant("fin-t", token="ft")],
        global_bound=4096, high_water=0, drr_quantum=4,
        start_worker=False)
    try:
        n = 0
        for i in range(0, len(h), 8):
            assert svc.submit("fk", h[i:i + 8], token="ft",
                              timeout=30)["accepted"]
            n += 1
        out = {}

        def fin():
            out["r"] = svc.finalize("fk", timeout=120, token="ft")

        t = th.Thread(target=fin)
        t.start()
        time.sleep(0.1)
        svc.start_worker()   # quantum 4 vs 8-op deltas: many cycles
        t.join(timeout=120)
        r = out["r"]
        assert r["seq"] == n, r
        assert _pin(r) == _pin(_oneshot(CASRegister, h))
    finally:
        svc.close()


def test_tenant_wal_quota_lifts_after_archiving(tmp_path):
    """Review pin: the WAL-bytes meter re-syncs from disk when the
    quota trips, so the documented operator relief — archiving the
    key's segments — actually lifts the quota without a restart."""
    from jepsen_tpu.serve import Tenant
    h = list(rand_register_history(n_ops=24, n_processes=3, seed=27))
    wal = str(tmp_path / "wal")
    svc = _tenant_svc(
        tenants=[Tenant("ar-w", token="aw", max_wal_bytes=64)],
        wal_dir=wal)
    try:
        assert svc.submit("wk", h[:8], token="aw",
                          timeout=60)["accepted"]
        svc.drain(timeout=60)
        r = svc.submit("wk", h[8:16], token="aw", timeout=30)
        assert r["shed"] is True and "WAL-bytes quota" in r["reason"]
        # the operator archives the key's segments (the WAL is the
        # durability record, so this is a deliberate, loud act)
        for name in os.listdir(wal):
            if name.endswith(".wal"):
                os.remove(os.path.join(wal, name))
        r2 = svc.submit("wk", h[8:16], token="aw", timeout=60)
        assert r2.get("accepted"), r2
    finally:
        svc.close()
