"""Generator DSL tests — property-level port of the reference's
jepsen.generator-test (jepsen/test/jepsen/generator_test.clj), using the
deterministic simulator (jepsen/src/jepsen/generator/test.clj)."""

import pytest

import jepsen_tpu.generator as gen
from jepsen_tpu.generator import Ctx, PENDING, fixed_rand
from jepsen_tpu.generator.testing import (
    default_context, imperfect, invocations, perfect, perfect_star,
    perfect_info, quick, quick_ops, simulate, PERFECT_LATENCY,
)


def ctx2():
    return default_context(2)


# ----------------------------------------------------------- base impls


def test_nil_generator():
    assert quick(None) == []


def test_map_one_shot():
    h = quick({"f": "write"})
    assert len(h) == 1
    op = h[0]
    assert op["f"] == "write"
    assert op["type"] == "invoke"
    assert op["time"] == 0
    assert op["process"] in (0, 1, "nemesis")


def test_map_with_explicit_fields():
    h = quick({"f": "w", "process": 1, "time": 5, "type": "invoke"})
    assert h[0]["process"] == 1
    assert h[0]["time"] == 5


def test_fn_generator_is_infinite():
    h = quick(gen.limit(5, lambda: {"f": "read"}))
    assert len(h) == 5
    assert all(o["f"] == "read" for o in h)


def test_fn_generator_two_arity():
    def g(test, ctx):
        return {"f": "read", "value": ctx.time}
    h = quick(gen.limit(3, g))
    assert len(h) == 3


def test_seq_generator():
    h = quick([{"f": "a"}, {"f": "b"}, {"f": "c"}])
    assert [o["f"] for o in h] == ["a", "b", "c"]


def test_nested_seqs():
    h = quick([[{"f": "a"}, {"f": "b"}], {"f": "c"}])
    assert [o["f"] for o in h] == ["a", "b", "c"]


# ---------------------------------------------------------- combinators


def test_limit_and_once():
    assert len(quick(gen.limit(3, lambda: {"f": "x"}))) == 3
    assert len(quick(gen.once(lambda: {"f": "x"}))) == 1


def test_repeat_map():
    # maps are one-shot; repeat makes them emit many times
    h = quick(gen.repeat(4, {"f": "read"}))
    assert len(h) == 4
    assert all(o["f"] == "read" for o in h)


def test_repeat_infinite_with_limit():
    h = quick(gen.limit(7, gen.repeat({"f": "read"})))
    assert len(h) == 7


def test_map_transform():
    h = quick(gen.map(lambda o: {**o, "value": 42},
                      gen.limit(2, lambda: {"f": "w", "value": None})))
    assert all(o["value"] == 42 for o in h)


def test_f_map():
    h = quick(gen.f_map({"start": "kill"}, gen.limit(2, lambda: {"f": "start"})))
    assert all(o["f"] == "kill" for o in h)


def test_filter():
    i = [0]

    def g():
        i[0] += 1
        return {"f": "x", "value": i[0]}

    h = quick(gen.limit(3, gen.filter(lambda o: o["value"] % 2 == 0, g)))
    assert [o["value"] for o in h] == [2, 4, 6]


def test_mix_draws_from_all():
    h = quick(gen.limit(200, gen.mix([lambda: {"f": "a"},
                                      lambda: {"f": "b"}])))
    fs = {o["f"] for o in h}
    assert fs == {"a", "b"}
    # roughly uniform
    n_a = sum(1 for o in h if o["f"] == "a")
    assert 40 <= n_a <= 160


def test_mix_exhaustion_compacts():
    h = quick(gen.mix([gen.limit(2, lambda: {"f": "a"}),
                       gen.limit(3, lambda: {"f": "b"})]))
    assert len(h) == 5
    assert sum(1 for o in h if o["f"] == "a") == 2


def test_any_prefers_soonest():
    # 'a' is scheduled later via delay; 'b' fires first
    g = gen.any(gen.delay(1, gen.limit(1, lambda: {"f": "a"})),
                gen.limit(1, lambda: {"f": "b"}))
    h = perfect(g)
    assert len(h) == 2


def test_flip_flop():
    g = gen.flip_flop(lambda: {"f": "a"}, lambda: {"f": "b"})
    h = quick(gen.limit(6, g))
    assert [o["f"] for o in h] == ["a", "b", "a", "b", "a", "b"]


def test_flip_flop_stops_on_exhaustion():
    g = gen.flip_flop(gen.limit(2, lambda: {"f": "a"}),
                      gen.limit(9, lambda: {"f": "b"}))
    h = quick(g)
    assert [o["f"] for o in h] == ["a", "b", "a", "b"]


# ------------------------------------------------------- thread routing


def test_clients_excludes_nemesis():
    h = quick(gen.clients(gen.limit(10, lambda: {"f": "r"})))
    assert all(o["process"] != "nemesis" for o in h)


def test_nemesis_only():
    h = quick(gen.nemesis(gen.limit(5, lambda: {"f": "kill"})))
    assert all(o["process"] == "nemesis" for o in h)


def test_clients_nemesis_two_arity():
    h = quick(gen.clients(gen.limit(10, lambda: {"f": "r"}),
                          gen.limit(3, lambda: {"f": "kill"})))
    client_ops = [o for o in h if o["process"] != "nemesis"]
    nem_ops = [o for o in h if o["process"] == "nemesis"]
    assert len(client_ops) == 10
    assert len(nem_ops) == 3
    assert all(o["f"] == "kill" for o in nem_ops)


def test_each_thread():
    h = quick(gen.each_thread(gen.once({"f": "read"})))
    # one op per thread: 2 workers + nemesis
    assert len(h) == 3
    assert {o["process"] for o in h} == {0, 1, "nemesis"}


def test_reserve():
    ctx = default_context(4)
    g = gen.reserve(2, gen.limit(100, lambda: {"f": "write"}),
                    gen.limit(100, lambda: {"f": "read"}))
    h = perfect(gen.time_limit(1, g), ctx)
    writes = {o["process"] for o in h if o["f"] == "write"}
    reads = {o["process"] for o in h if o["f"] == "read"}
    assert writes and writes <= {0, 1}
    # default gets threads 2,3 + nemesis
    assert reads and reads <= {2, 3, "nemesis"}


def test_on_threads_restricts_context():
    g = gen.on_threads(lambda t: t == 0, gen.limit(5, lambda: {"f": "r"}))
    h = quick(g)
    assert all(o["process"] == 0 for o in h)


# --------------------------------------------------------- time shaping


def test_stagger_spreads_ops():
    g = gen.stagger(1, gen.limit(10, lambda: {"f": "r"}))
    h = perfect(g)
    times = [o["time"] for o in h]
    assert times == sorted(times)
    assert times[-1] > 0  # spread out, not all at 0


def test_delay_fixed_rate():
    g = gen.delay(1, gen.limit(4, lambda: {"f": "r"}))
    h = perfect(g)
    times = [o["time"] for o in h]
    s = int(1e9)
    assert times == [0, s, 2 * s, 3 * s]


def test_time_limit():
    g = gen.time_limit(1, gen.delay(0.3, lambda: {"f": "r"}))
    h = perfect(g)
    # ops at 0, .3, .6, .9 s; 1.2 is past the limit
    assert len(h) == 4


def test_process_limit():
    # every op crashes -> each completion burns a process; with
    # concurrency 2 + nemesis = 3 processes seen immediately, crashed
    # client threads get fresh ids until the union exceeds n.
    g = gen.clients(gen.process_limit(4, lambda: {"f": "r"}))
    h = perfect_info(g)
    assert 0 < len(h) <= 4


# ------------------------------------------------------------- barriers


def test_phases_synchronize():
    g = gen.phases(gen.limit(4, lambda: {"f": "a"}),
                   gen.limit(2, lambda: {"f": "b"}))
    h = perfect_star(g)
    # every 'b' invocation comes after every 'a' completion
    a_completions = [o["time"] for o in h
                     if o["f"] == "a" and o["type"] == "ok"]
    b_invokes = [o["time"] for o in h
                 if o["f"] == "b" and o["type"] == "invoke"]
    assert b_invokes and a_completions
    assert min(b_invokes) >= max(a_completions)


def test_then():
    g = gen.then(gen.once({"f": "b"}), gen.limit(3, lambda: {"f": "a"}))
    h = perfect(g)
    assert [o["f"] for o in h] == ["a", "a", "a", "b"]


def test_until_ok():
    # imperfect completes fail, info, ok, fail... per thread
    g = gen.on_threads(lambda t: t == 0,
                       gen.until_ok(lambda: {"f": "r"}))
    h = imperfect(g)
    oks = [o for o in h if o["type"] == "ok"]
    assert len(oks) == 1
    # nothing after the first ok
    assert h[-1]["type"] == "ok"


# ------------------------------------------------------------ validation


def test_validate_rejects_bad_type():
    with pytest.raises(gen.InvalidOp):
        quick({"f": "w", "type": "bogus"})


def test_validate_rejects_busy_process():
    # two back-to-back ops pinned to process 0: the second is requested
    # while process 0 is still executing the first (perfect latency 10ns)
    g = [{"f": "a", "process": 0}, {"f": "b", "process": 0}]
    with pytest.raises(gen.InvalidOp):
        perfect(g)


def test_friendly_exceptions_wrap():
    def boom():
        raise ValueError("boom")

    with pytest.raises(gen.GeneratorThrew):
        quick(gen.friendly_exceptions(gen.Map(lambda o: boom(),
                                              gen.once({"f": "x"}))))


# --------------------------------------------------------- determinism


def test_simulate_deterministic():
    # mix draws its initial index at construction time, so construction
    # must be seeded too for bitwise-identical histories
    def make():
        with fixed_rand(7):
            return gen.stagger(0.1, gen.limit(50, gen.mix(
                [lambda: {"f": "a"}, lambda: {"f": "b"}])))
    h1 = perfect_star(make())
    h2 = perfect_star(make())
    assert h1 == h2


def test_crashed_processes_get_fresh_ids():
    h = perfect_info(gen.clients(gen.limit(6, lambda: {"f": "r"})))
    procs = [o["process"] for o in h]
    # processes never reused after crashing
    assert len(set(procs)) == len(procs)


def test_perfect_latency_completions():
    h = perfect_star(gen.clients(gen.limit(2, lambda: {"f": "r"})))
    invs = [o for o in h if o["type"] == "invoke"]
    oks = [o for o in h if o["type"] == "ok"]
    assert len(invs) == 2 and len(oks) == 2
    for inv, ok in zip(invs, oks):
        assert ok["time"] - inv["time"] <= 2 * PERFECT_LATENCY


# ---------------------------------------------------------- on_update


def test_on_update_swaps_generator():
    # after the first completion event, switch to reads
    def handler(this, test, ctx, event):
        if event.get("type") == "ok":
            return gen.limit(2, lambda: {"f": "read"})
        return this

    g = gen.on_update(handler, gen.repeat({"f": "write"}))
    h = perfect(gen.clients(g))
    fs = [o["f"] for o in h]
    assert fs[0] == "write"
    assert fs.count("read") == 2
    assert len(fs) <= 4


@pytest.mark.slow
def test_pure_generator_rate_beats_reference_claim():
    """The reference documents >20,000 ops/sec single-threaded pure
    generation (generator.clj:68-70); this build measures ~50k on a
    dev container. Floor at the reference's claim so a combinator
    regression that halves generation throughput fails loudly."""
    import time

    def make():
        return gen.limit(30000, gen.mix([
            lambda: {"f": "write", "value": 1},
            lambda: {"f": "read", "value": None}]))

    quick_ops(make())                            # warm
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        h = quick_ops(make())
        best = max(best, len(h) / (time.perf_counter() - t0))
    assert best > 20_000, f"generation rate {best:.0f} ops/s below " \
                          f"the reference's documented 20k floor"


# ------------------------- rand_*_history invocation-vs-op contract


def test_rand_history_n_ops_counts_invocations_not_rows():
    """THE n_ops contract (docs + the phantom-parity-bug regression):
    ``rand_*_history(n_ops=N)`` generates N INVOCATIONS — like the
    reference's generators count :invoke entries — and every
    invocation gets exactly one completion row (ok/fail/info), so the
    returned history has exactly 2N rows. A caller slicing the result
    by ``n_ops`` gets HALF the stream with calls dangling open — a
    valid prefix (so nothing crashes), which is exactly why the
    mistake reads like a checker parity bug instead of what it is.
    Pinned here so the contract can never drift silently."""
    from jepsen_tpu.histories import (
        rand_fifo_history, rand_gset_history, rand_queue_history,
        rand_register_history,
    )
    for make in (rand_register_history, rand_gset_history,
                 rand_queue_history, rand_fifo_history):
        for n in (1, 10, 37):
            ops = list(make(n_ops=n, n_processes=4, seed=11))
            invokes = [o for o in ops if o["type"] == "invoke"]
            completions = [o for o in ops
                           if o["type"] in ("ok", "fail", "info")]
            assert len(invokes) == n, (make.__name__, n, len(invokes))
            assert len(completions) == n, (make.__name__, n)
            assert len(ops) == 2 * n, (make.__name__, n, len(ops))
        # the hazard itself: an n_ops slice truncates mid-stream —
        # strictly fewer completions than calls, i.e. NOT the history
        # the caller thinks it compared
        ops = list(make(n_ops=20, n_processes=4, seed=11))
        sliced = ops[:20]
        n_inv = sum(1 for o in sliced if o["type"] == "invoke")
        n_done = len(sliced) - n_inv
        assert n_done < n_inv, \
            f"{make.__name__}: an n_ops slice should leave calls open"
