"""Self-tuning strategy planner (JEPSEN_TPU_AUTO, ISSUE 20).

Pins the contracts docs/performance.md "Auto planner" documents:

- flag off (unset/"0"): no planner, no ``plan`` provenance, no
  ``plan_table.json``, no ``engine.plan.*`` metric movement — results
  identical to the pre-planner tree;
- flag on: axes the caller left None route through the per-shape
  decision table; explicit arguments are never overridden; every arm
  is parity-pinned, so a plan (including an exploration) can change
  wall-clock only, never the verdict;
- floor semantics: below ``JEPSEN_TPU_LEDGER_FLOOR`` samples the
  static defaults run (source ``floor-default``) while the dispatch
  still contributes EWMA evidence;
- durability: the table persists atomically beside the ledger
  segments; a truncated/garbage/stale-schema file degrades to a
  counted re-seed, never a crash;
- provenance: planned results carry the ``plan`` block, every
  decision mints a ``kind=plan`` ledger record, and the live table is
  served on the ops ``/plan`` endpoint.
"""

import json
import os

import pytest

from jepsen_tpu import envflags, obs
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.obs import ledger
from jepsen_tpu.parallel import encode as enc_mod, engine, planner

# Dedupe arms legitimately differ in configs-stepped/explored — the
# cross-arm pin is the perf_ab/serve parity surface.
PIN = ("valid?", "op", "fail-event", "max-frontier")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _strip_plan(r):
    return {k: v for k, v in r.items() if k != "plan"}


def _mem_planner(**kw):
    """An in-memory planner: no durable root, no bench seeding."""
    kw.setdefault("bench_dir", "")
    return planner.Planner(None, **kw)


_G = ("sparse", "register_step", 6)   # a shape group for unit tests


@pytest.fixture(autouse=True)
def _fresh(monkeypatch):
    for flag in ("JEPSEN_TPU_AUTO", "JEPSEN_TPU_AUTO_EXPLORE",
                 "JEPSEN_TPU_LEDGER", "JEPSEN_TPU_LEDGER_FLOOR"):
        monkeypatch.delenv(flag, raising=False)
    planner.reset()
    ledger.reset()
    yield
    planner.reset()
    ledger.reset()


# ------------------------------------------------------- table mechanics


def test_floor_defaults_then_online_takeover():
    pl = _mem_planner(floor=3, explore_every=0)
    dec = pl.decide(*_G, {"dedupe": None})
    assert dec["plan"]["source"] == "floor-default"
    assert dec["strategy"] == {"dedupe": "sort"}
    # below-floor dispatches still contribute evidence ...
    for _ in range(2):
        pl.observe(*_G, {"dedupe": "hash"}, 0.01)
        pl.observe(*_G, {"dedupe": "sort"}, 0.50)
    dec = pl.decide(*_G, {"dedupe": None})
    assert dec["plan"]["source"] == "floor-default"   # n=2 < floor
    # ... and once a cell clears the floor the cheapest arm wins
    pl.observe(*_G, {"dedupe": "hash"}, 0.01)
    pl.observe(*_G, {"dedupe": "sort"}, 0.50)
    dec = pl.decide(*_G, {"dedupe": None})
    assert dec["strategy"] == {"dedupe": "hash"}
    assert dec["plan"]["source"] == "online"
    assert dec["plan"]["cell_n"] == 3
    assert dec["plan"]["explored"] is False


def test_explicit_axis_is_never_overridden():
    pl = _mem_planner(floor=1, explore_every=0)
    for _ in range(2):
        pl.observe("sparse", "f", 4, {"dedupe": "hash", "pack": True},
                   0.01)
    # the caller fixed dedupe=sort: the (faster) hash cell is
    # incompatible, so only pack is plannable and it floor-defaults
    dec = pl.decide("sparse", "f", 4, {"dedupe": "sort", "pack": None})
    assert "dedupe" not in dec["strategy"]
    assert dec["strategy"] == {"pack": False}
    assert dec["plan"]["source"] == "floor-default"
    # nothing plannable -> no decision at all
    assert pl.decide("sparse", "f", 4, {"dedupe": "sort"}) is None


def test_sanitize_never_pairs_pallas_with_sort():
    assert planner._sanitize({"dedupe": "sort", "pallas": True}) \
        == {"dedupe": "sort", "pallas": False}
    assert planner._sanitize({"dedupe": "hash", "pallas": True}) \
        == {"dedupe": "hash", "pallas": True}


def test_exploration_cadence_is_deterministic():
    pl = _mem_planner(floor=1, explore_every=2)
    for _ in range(2):
        pl.observe(*_G, {"dedupe": "hash"}, 0.01)
        pl.observe(*_G, {"dedupe": "sort"}, 0.50)
    before = obs.counter("engine.plan.explorations").value
    d1 = pl.decide(*_G, {"dedupe": None})
    d2 = pl.decide(*_G, {"dedupe": None})
    assert d1["plan"]["explored"] is False
    assert d1["strategy"] == {"dedupe": "hash"}      # the best arm
    assert d2["plan"]["explored"] is True
    assert d2["strategy"] == {"dedupe": "sort"}      # the alternative
    assert obs.counter("engine.plan.explorations").value == before + 1


def test_ewma_matches_elastic_smoothing():
    # planner cells and the stealing scheduler's cohort predictions
    # share one estimator (docs/performance.md "Auto planner")
    assert planner.ewma_update(None, 0.1) == pytest.approx(0.1)
    assert planner.ewma_update(0.1, 0.2) == pytest.approx(0.15)
    pl = _mem_planner(floor=1)
    pl.observe(*_G, {"dedupe": "sort"}, 0.1)
    pl.observe(*_G, {"dedupe": "sort"}, 0.2)
    cell = pl.table[planner.group_key(*_G)]["cells"]["dedupe=sort"]
    assert cell["ewma"] == pytest.approx(0.15)


# ---------------------------------------------------------- durability


def test_table_durable_roundtrip(tmp_path):
    root = str(tmp_path)
    pl = planner.Planner(root, bench_dir="", floor=1, explore_every=0)
    pl.observe("sparse", "f", 4, {"dedupe": "hash"}, 0.02)
    doc = planner.load_table(root)
    assert doc["version"] == planner.TABLE_VERSION
    cell = doc["groups"]["engine=sparse,family=f,C=4"]["cells"][
        "dedupe=hash"]
    assert cell["n"] == 1 and cell["ewma"] == pytest.approx(0.02)
    # a fresh process adopts the durable evidence
    pl2 = planner.Planner(root, bench_dir="", floor=1, explore_every=0)
    dec = pl2.decide("sparse", "f", 4, {"dedupe": None})
    assert dec["strategy"] == {"dedupe": "hash"}
    assert dec["plan"]["cell_n"] == 1


@pytest.mark.parametrize("payload", [
    '{"version": 1, "gro',                 # truncated mid-write
    "\x00\x01 not json at all",            # garbage bytes
    '{"version": 99, "groups": {}}',       # stale schema version
    "[1, 2, 3]",                           # wrong document shape
], ids=["truncated", "garbage", "stale-version", "wrong-shape"])
def test_corrupt_table_reseeds_counted_never_crashes(tmp_path, payload):
    root = str(tmp_path)
    with open(ledger.plan_table_path(root), "w") as fh:
        fh.write(payload)
    before = obs.counter("engine.plan.reseeds").value
    pl = planner.Planner(root, bench_dir="")
    assert obs.counter("engine.plan.reseeds").value == before + 1
    # the rewritten table is valid again and the planner is usable
    assert planner.load_table(root) is not None
    dec = pl.decide("e", "f", 4, {"dedupe": None})
    assert dec["plan"]["source"] == "floor-default"


def test_malformed_flag_raises_loudly(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_AUTO", "yes")
    planner.reset()
    with pytest.raises(envflags.EnvFlagError):
        planner.active()


# -------------------------------------------------------- flag off/on


def test_flag_off_is_inert(tmp_path, monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_LEDGER", str(tmp_path))
    ledger.reset()
    assert planner.active() is None
    assert planner.plan_doc() == {"auto": {"enabled": False},
                                  "groups": {}}
    before = obs.counter("engine.plan.decisions").value
    h = rand_register_history(n_ops=40, n_processes=5, n_values=3,
                              crash_p=0.05, fail_p=0.05, seed=9)
    r = engine.check_encoded(enc_mod.encode(CASRegister(), h),
                             capacity=256, max_capacity=1024)
    assert "plan" not in r
    assert obs.counter("engine.plan.decisions").value == before
    assert not os.path.exists(ledger.plan_table_path(str(tmp_path)))
    led = ledger.active()
    led.sync()
    recs, _ = ledger.read_records(str(tmp_path))
    assert not any(rec.get("kind") == "plan" for rec in recs)


def test_auto_check_encoded_parity_and_exploration(monkeypatch):
    """Engine-level: planned dispatches (including forced every-turn
    exploration) pin the static verdict surface on clean AND
    corrupted histories."""
    m = CASRegister()
    clean = rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                  crash_p=0.05, fail_p=0.05, seed=9)
    bad = corrupt_history(
        rand_register_history(n_ops=40, n_processes=5, n_values=3,
                              crash_p=0.05, fail_p=0.05, seed=10),
        seed=2, n_corruptions=2)
    for h in (clean, bad):
        e = enc_mod.encode(m, h)
        refs = {s: engine.check_encoded(e, capacity=256,
                                        max_capacity=1024, dedupe=s)
                for s in ("sort", "hash")}
        base = _pin(refs["sort"])
        assert _pin(refs["hash"]) == base
        monkeypatch.setenv("JEPSEN_TPU_AUTO", "1")
        monkeypatch.setenv("JEPSEN_TPU_AUTO_EXPLORE", "1")
        planner.reset()
        for _ in range(4):
            r = engine.check_encoded(e, capacity=256,
                                     max_capacity=1024)
            assert _pin(r) == base
            p = r["plan"]
            assert set(p) == {"vector", "cell_n", "source", "explored"}
            assert p["source"] in ("floor-default", "seeded", "online")
        monkeypatch.delenv("JEPSEN_TPU_AUTO")
        monkeypatch.delenv("JEPSEN_TPU_AUTO_EXPLORE")
        planner.reset()


def test_auto_check_batch_plans_executor_axes(monkeypatch):
    m = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=4, n_values=3,
                                crash_p=0.05, fail_p=0.05, seed=s)
          for s in (21, 22)]
    ref = engine.check_batch(m, hs, capacity=256, max_capacity=1024)
    monkeypatch.setenv("JEPSEN_TPU_AUTO", "1")
    planner.reset()
    rs = engine.check_batch(m, hs, capacity=256, max_capacity=1024)
    assert [_pin(r) for r in rs] == [_pin(r) for r in ref]
    # the batch-level decision landed in its own shape group and the
    # dispatch fed the executor-arm cell
    tbl = planner.active().table
    grp = tbl[planner.group_key("batch", "CASRegister", None)]
    assert grp["decisions"] >= 1
    (cell,) = grp["cells"].values()
    assert cell["arm"] == {"pipeline": False, "steal": False}
    assert cell["n_live"] == 1


def test_auto_stream_parity_provenance_and_ledger(tmp_path,
                                                 monkeypatch):
    """A live HistorySession under AUTO is byte-identical to the
    static session once the plan provenance block is stripped, and
    the decision leaves the full durable trail (kind=plan record +
    plan_table.json beside the segments)."""
    from jepsen_tpu.parallel.extend import HistorySession
    m = CASRegister()
    ops = list(rand_register_history(n_ops=60, n_processes=5,
                                     n_values=4, crash_p=0.03,
                                     fail_p=0.05, seed=13))
    n = len(ops) // 3
    s = HistorySession(m, capacity=256)
    outs = []
    for i in range(3):
        s.extend(ops[i * n:(i + 1) * n if i < 2 else len(ops)])
        outs.append(s.check())

    monkeypatch.setenv("JEPSEN_TPU_AUTO", "1")
    monkeypatch.setenv("JEPSEN_TPU_LEDGER", str(tmp_path))
    planner.reset()
    ledger.reset()
    before = obs.counter("engine.plan.decisions").value
    s2 = HistorySession(m, capacity=256)
    outs2 = []
    for i in range(3):
        s2.extend(ops[i * n:(i + 1) * n if i < 2 else len(ops)])
        outs2.append(s2.check())
    for a, b in zip(outs, outs2):
        assert _strip_plan(b) == _strip_plan(a)
    # the plan is decided once per session and pinned for its lifetime
    assert obs.counter("engine.plan.decisions").value == before + 1
    for b in outs2:
        assert set(b["plan"]) == {"vector", "cell_n", "source",
                                  "explored"}
    # durable trail: a kind=plan record and the table beside the
    # segments
    ledger.active().sync()
    recs, corrupt = ledger.read_records(str(tmp_path))
    assert corrupt == 0
    plans = [r for r in recs if r.get("kind") == "plan"]
    assert len(plans) == 1
    assert plans[0]["engine"] == "stream"
    assert set(plans[0]["strategy"]) <= set(planner.AXES)
    assert planner.load_table(str(tmp_path)) is not None


# ------------------------------------------------------- ops surfaces


def test_plan_endpoint_off_and_on(monkeypatch):
    from jepsen_tpu.obs import httpd
    srv = httpd.start_ops_server(0)
    try:
        code, body = httpd._fetch(srv.url("/plan"))
        doc = json.loads(body)
        assert code == 200
        assert doc == {"auto": {"enabled": False}, "groups": {}}
        monkeypatch.setenv("JEPSEN_TPU_AUTO", "1")
        planner.reset()
        planner.active().observe(*_G, {"dedupe": "hash"}, 0.02)
        code, body = httpd._fetch(srv.url("/plan"))
        doc = json.loads(body)
        assert code == 200 and doc["auto"]["enabled"] is True
        cells = doc["groups"][planner.group_key(*_G)]["cells"]
        assert cells["dedupe=hash"]["n"] == 1
    finally:
        srv.close()


def test_elastic_ewma_cost_gauge():
    from jepsen_tpu.parallel import elastic
    ks = elastic.KeyScheduler(range(4), n_dev=2, round_keys=2,
                              steal=True)
    placement = ks.next_round()
    ks.observe({i: 0.1 * (i + 1) for i, _ in placement})
    # cohort 0 saw keys 0 and 1 (0.1 then 0.2): the planner's shared
    # estimator folds them to 0.15, published per cohort on /metrics
    assert ks.pred[0] == pytest.approx(
        planner.ewma_update(planner.ewma_update(None, 0.1), 0.2))
    snap = obs.registry().snapshot()
    g = snap[obs.labeled("elastic.ewma_cost", cohort="0")]
    assert g["value"] == pytest.approx(0.15)


# ------------------------------------------------------- convergence


@pytest.mark.slow
def test_auto_converges_to_winning_arm_live(tmp_path, monkeypatch):
    """Convergence pin: prime both dedupe cells with real dispatches
    under AUTO (explicit arms — the planner only observes), then let
    it decide: it must route to whichever arm the table measured
    cheaper, with online provenance, and stay there with exploration
    off."""
    monkeypatch.setenv("JEPSEN_TPU_AUTO", "1")
    monkeypatch.setenv("JEPSEN_TPU_AUTO_EXPLORE", "0")
    monkeypatch.setenv("JEPSEN_TPU_LEDGER", str(tmp_path))
    planner.reset()
    ledger.reset()
    m = CASRegister()
    h = rand_register_history(n_ops=40, n_processes=5, n_values=3,
                              crash_p=0.05, fail_p=0.05, seed=9)
    e = enc_mod.encode(m, h)
    for arm in ("sort", "hash"):
        for _ in range(3):
            engine.check_encoded(e, capacity=256, max_capacity=1024,
                                 dedupe=arm, sparse_pallas=False,
                                 config_pack=False)
    pl = planner.active()
    grp = pl.table[planner.group_key("sparse", e.step_name,
                                     e.slot_f.shape[1])]
    cells = {sig: c for sig, c in grp["cells"].items()
             if c["ewma"] is not None and c["n"] >= pl.floor}
    assert len(cells) >= 2
    winner = min(cells, key=lambda s: (cells[s]["ewma"], s))
    for _ in range(3):
        r = engine.check_encoded(e, capacity=256, max_capacity=1024)
        assert r["plan"]["source"] == "online"
        assert r["plan"]["explored"] is False
        assert r["dedupe"] == cells[winner]["arm"]["dedupe"]
