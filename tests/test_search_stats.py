"""Device-resident search telemetry (JEPSEN_TPU_SEARCH_STATS).

The ISSUE 10 contracts:

1. PARITY — stats-on vs stats-off results are identical (verdict,
   op/fail-event, max-frontier, configs-stepped, every key) across
   the five packable families x sort/hash dedupe x the
   serial/pipelined/sharded/resumable/streaming paths; stats-off
   result dicts carry NO "stats" key (byte-identical schema).
2. SCHEMA — the "stats" block's fields are pinned (the four sinks'
   consumers read them); trajectories cover exactly the real events.
3. SINKS — /metrics serves jepsen_engine_search_*; the Chrome trace
   gains "C" counter-track events (engine.search.* only with the flag
   on, pipeline.inflight / breaker state / serve queue depth from the
   satellite sample sites); export_run writes search_stats.jsonl and
   `jepsen report --search` renders it.
4. NO-OP — with the flag unset the disabled paths meet the PR-4
   standard: counter_sample with tracing off retains zero allocations,
   and no engine.search series/tracks/records appear anywhere.
"""

import json
import os
import tracemalloc

import numpy as np
import pytest

from jepsen_tpu import envflags, obs
from jepsen_tpu.histories import (corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import engine


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    import jepsen_tpu.obs.export as export_mod

    monkeypatch.delenv("JEPSEN_TPU_TRACE", raising=False)
    monkeypatch.delenv("JEPSEN_TPU_SEARCH_STATS", raising=False)
    obs.reset()
    obs.drain_search_stats()
    export_mod._last_reg_snapshot = {}
    yield
    obs.reset()
    obs.registry().reset()
    obs.drain_search_stats()
    export_mod._last_reg_snapshot = {}


def _h(*ops):
    return History.wrap(ops).index()


FAMILIES = [
    ("register", CASRegister(),
     lambda s: rand_register_history(n_ops=28, n_processes=4,
                                     n_values=3, crash_p=0.05,
                                     fail_p=0.05, seed=s)),
    ("gset", GSet(),
     lambda s: rand_gset_history(n_ops=24, n_processes=4, n_elements=5,
                                 crash_p=0.06, seed=s)),
    ("uqueue", UnorderedQueue(),
     lambda s: rand_queue_history(n_ops=24, n_processes=4, n_values=3,
                                  crash_p=0.06, seed=s)),
    ("fifo", FIFOQueue(),
     lambda s: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                                 crash_p=0.15, seed=s)),
]


def _mutex_invalid():
    return _h(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
              invoke_op(1, "acquire", None), ok_op(1, "acquire", None))


# ----------------------------------------------------------- parity


@pytest.mark.parametrize("name,model,gen", FAMILIES,
                         ids=[f[0] for f in FAMILIES])
@pytest.mark.parametrize("dedupe", ["sort", "hash"])
def test_parity_check_encoded_families(name, model, gen, dedupe):
    """Stats on/off verdict parity + schema pin, clean and corrupted,
    sparse engine."""
    for i, h in enumerate([gen(3), corrupt_history(gen(9), seed=1,
                                                   n_corruptions=2)]):
        e = enc_mod.encode(model, History.wrap(h))
        r_off = engine.check_encoded(e, capacity=128, dedupe=dedupe)
        r_on = engine.check_encoded(e, capacity=128, dedupe=dedupe,
                                    search_stats=True)
        assert "stats" not in r_off
        s = r_on.pop("stats")
        assert r_off == r_on, (name, dedupe, i, r_off, r_on)
        _pin_schema(s, e, dedupe)


def _pin_schema(s, e, dedupe):
    """The stats-block schema pin (the contract every sink reads)."""
    assert s["events"] == len(s["frontier-width"]) \
        == len(s["closure-iters"]) \
        == len(s["configs-stepped-per-event"]) == len(s["closure-peak"])
    # the trajectory stops at the failing event, never past R
    assert 0 < s["events"] <= e.n_returns
    assert s["frontier-peak"] == max(s["closure-peak"])
    assert s["dedupe"] == dedupe
    assert s["capacity"] >= 64 and s["capacity-tier"] >= 0
    assert 0 < s["peak-occupancy"] <= 1
    assert sum(s["configs-stepped-per-event"]) > 0
    if dedupe == "hash":
        assert s["table-capacity"] == engine._next_pow2(
            2 * s["capacity"])
        assert 0 < s["load-factor-peak"] <= 0.5 + 1e-9
        assert set(s["probe-hist"]) == set(engine.PROBE_HIST_LABELS)
        assert s["probes"] == sum(s["probe-hist"].values()) > 0
        assert 0 < s["delta-split-ratio"] <= 1.0
    else:
        assert s["table-capacity"] is None
        assert s["load-factor-peak"] is None
        assert s["probe-hist"] is None
        assert s["delta-split-ratio"] == 1.0


def test_parity_mutex_invalid_and_sparse_pallas():
    m = Mutex()
    e = enc_mod.encode(m, _mutex_invalid())
    r_off = engine.check_encoded(e, capacity=64, dedupe="hash")
    r_on = engine.check_encoded(e, capacity=64, dedupe="hash",
                                search_stats=True)
    s = r_on.pop("stats")
    assert r_off == r_on and r_off["valid?"] is False
    # the failing event closes the trajectory with width 0
    assert s["frontier-width"][-1] == 0
    # the fused pallas kernel (interpret) computes the SAME stats
    r_pk = engine.check_encoded(e, capacity=64, dedupe="hash",
                                sparse_pallas=True, search_stats=True)
    s_pk = r_pk.pop("stats")
    assert r_pk["closure"] == "pallas"
    s_pk.pop("engine"), s.pop("engine")
    assert s_pk == s


def test_parity_batch_and_pipelined():
    model = CASRegister()
    hs = [rand_register_history(n_ops=26, n_processes=4, crash_p=0.04,
                                seed=500 + s) for s in range(5)]
    hs[2] = corrupt_history(hs[2], seed=2, n_corruptions=2)
    r_off = engine.check_batch(model, hs)
    r_on = engine.check_batch(model, hs, search_stats=True)
    for a, b in zip(r_off, r_on):
        assert "stats" not in a
        s = b.pop("stats")
        assert a == b
        # bitdense batch: dense engine block + pad-waste fields
        assert s["engine"] == "bitdense" and s["dedupe"] == "dense"
        assert 0 <= s["pad-waste"] < 1 and s["pad-events"] >= 0
        assert s["events"] >= 1
    # pipelined executor: same verdicts, same per-key trajectories
    # (chunks pad to the bucket dims, pads filter out on device)
    r_on2 = engine.check_batch(model, hs, search_stats=True)
    r_pipe = engine.check_batch(model, hs, pipeline=True, cache=False,
                                search_stats=True)
    for a, b in zip(r_on2, r_pipe):
        sa, sb = a.pop("stats"), b.pop("stats")
        assert a == b
        assert sa["frontier-width"] == sb["frontier-width"]


def test_parity_sparse_batch_pad_waste():
    """_check_batch_sparse: per-key stats + pad-waste measured against
    the padded program dims."""
    model = CASRegister()
    pres = [enc_mod.encode(model, History.wrap(
        rand_register_history(n_ops=18 + 8 * s, n_processes=4,
                              seed=600 + s))) for s in range(3)]
    r_off = engine._check_batch_sparse(model, pres, 128, 1 << 18,
                                       dedupe="hash")
    r_on = engine._check_batch_sparse(model, pres, 128, 1 << 18,
                                      dedupe="hash", search_stats=True)
    R_pad = max(e.n_returns for e in pres)
    C_pad = max(e.slot_f.shape[1] for e in pres)
    blocks = []
    for e, a, b in zip(pres, r_off, r_on):
        s = b.pop("stats")
        blocks.append(s)
        assert a == b
        assert s["events"] == e.n_returns
        want = 1.0 - (e.n_returns * e.slot_f.shape[1]) / (R_pad * C_pad)
        assert s["pad-waste"] == pytest.approx(want, abs=1e-6)
    # the biggest key pads nothing
    big = max(range(3), key=lambda i: pres[i].n_returns)
    assert blocks[big]["pad-events"] == 0


def test_parity_bitdense_single():
    model = CASRegister()
    h = rand_register_history(n_ops=30, n_processes=4, seed=7)
    e = enc_mod.encode(model, History.wrap(h))
    r_off = engine.analysis(model, h)
    r_on = engine.analysis(model, h, search_stats=True)
    s = r_on.pop("stats")
    assert r_off == r_on and r_off["engine"] == "bitdense"
    assert s["engine"] == "bitdense"
    assert s["events"] == e.n_returns
    assert s["config-space"] == r_off["states"] * (1 << r_off["slots"])
    assert s["frontier-peak"] == max(s["frontier-width"])
    assert 0 < s["peak-occupancy"] <= 1


def test_parity_sharded():
    import jax
    from jax.sharding import Mesh
    from jepsen_tpu.parallel import sharded

    model = CASRegister()
    h = rand_register_history(n_ops=36, n_processes=4, crash_p=0.05,
                              seed=21)
    e = enc_mod.encode(model, History.wrap(h))
    mesh = Mesh(np.array(jax.devices()[:4]), ("frontier",))
    for dedupe in ("sort", "hash"):
        r_off = sharded.check_encoded_sharded(e, mesh, capacity=256,
                                              dedupe=dedupe)
        r_on = sharded.check_encoded_sharded(e, mesh, capacity=256,
                                             dedupe=dedupe,
                                             search_stats=True)
        s = r_on.pop("stats")
        assert r_off == r_on
        assert s["engine"] == "sharded" and s["devices"] == 4
        assert s["events"] == e.n_returns
        # mesh-reduced peak equals the result's global max-frontier
        assert s["frontier-peak"] == r_off["max-frontier"]
        assert len(s["per-device"]["width-peak"]) == 4
        if dedupe == "hash":
            assert s["probes"] > 0
            assert len(s["per-device"]["load-factor-peak"]) == 4
    # sharded stats agree with the single-device engine's trajectory
    r1 = engine.check_encoded(e, capacity=256, dedupe="hash",
                              search_stats=True)
    assert s["frontier-width"] == r1["stats"]["frontier-width"]


def test_parity_resumable_and_stream_lifetime():
    model = CASRegister()
    h = list(rand_register_history(n_ops=40, n_processes=4,
                                   crash_p=0.05, seed=31))
    e = enc_mod.encode(model, History.wrap(h))
    ref = engine.check_encoded(e, capacity=128, dedupe="hash",
                               search_stats=True)
    r_off = engine.check_encoded_resumable(e, capacity=128,
                                           checkpoint_every=8,
                                           dedupe="hash")
    r_on = engine.check_encoded_resumable(e, capacity=128,
                                          checkpoint_every=8,
                                          dedupe="hash",
                                          search_stats=True)
    s = r_on.pop("stats")
    assert r_off == r_on
    for k in ("frontier-width", "closure-iters", "probe-hist",
              "configs-stepped-per-event"):
        assert s[k] == ref["stats"][k], k

    # streaming session: lifetime stats == the one-shot block of the
    # full prefix, across deltas and the splice-at-resume re-scan
    from jepsen_tpu.parallel.extend import HistorySession
    n = len(h)
    s0 = HistorySession(model, capacity=128, dedupe="hash")
    s1 = HistorySession(model, capacity=128, dedupe="hash",
                        search_stats=True, key="k")
    last = None
    for a, b in [(0, n // 3), (n // 3, 2 * n // 3), (2 * n // 3, n)]:
        s0.extend(h[a:b]), s1.extend(h[a:b])
        r0, r1 = s0.check(), s1.check()
        last = r1.pop("stats")
        assert r0 == r1
    assert last["engine"] == "stream"
    for k in ("frontier-width", "closure-iters", "probe-hist",
              "delta-split-ratio"):
        assert last[k] == ref["stats"][k], k


def test_parity_batched_advance():
    from jepsen_tpu.parallel.extend import HistorySession, \
        advance_sessions

    model = CASRegister()
    hs = [list(rand_register_history(n_ops=28, n_processes=4,
                                     seed=700 + i)) for i in range(3)]
    ss = [HistorySession(model, capacity=128, dedupe="hash",
                         search_stats=True, key=f"k{i}")
          for i in range(3)]
    refs = [HistorySession(model, capacity=128, dedupe="hash")
            for _ in range(3)]
    for half in (0, 1):
        for s, sr, h in zip(ss, refs, hs):
            k = len(h) // 2
            d = h[:k] if half == 0 else h[k:]
            s.extend(d), sr.extend(d)
        rs = advance_sessions(ss)
        rrs = [sr.check() for sr in refs]
    for r, rr, h in zip(rs, rrs, hs):
        st = r.pop("stats")
        assert r == rr
        e = enc_mod.encode(model, History.wrap(h))
        one = engine.check_encoded(e, capacity=128, dedupe="hash",
                                   search_stats=True)["stats"]
        assert st["frontier-width"] == one["frontier-width"]


def test_parity_independent_per_key_stats():
    from jepsen_tpu import independent
    from jepsen_tpu.checker import linearizable
    from jepsen_tpu.history import invoke_op as inv, ok_op as ok
    from jepsen_tpu.independent import KV

    ops = [inv(0, "write", KV("x", 1)), ok(0, "write", KV("x", 1)),
           inv(0, "read", KV("x", None)), ok(0, "read", KV("x", 1)),
           inv(1, "write", KV("y", 2)), ok(1, "write", KV("y", 2)),
           inv(1, "read", KV("y", None)), ok(1, "read", KV("y", 5))]
    h = History.wrap(ops).index()
    lin = linearizable(CASRegister(), algorithm="jax")
    r_off = independent.checker(lin).check({}, h)
    r_on = independent.checker(lin, search_stats=True).check({}, h)
    assert r_off["valid?"] is r_on["valid?"] is False
    for k in ("x", "y"):
        s = r_on["results"][k].pop("stats")
        assert s["events"] >= 1 and s["engine"] == "bitdense"
        assert "stats" not in r_off["results"][k]
    assert r_on["failures"] == r_off["failures"] == ["y"]


# ------------------------------------------------------------- sinks


def test_metrics_registry_and_prometheus():
    from jepsen_tpu.obs import httpd

    model = CASRegister()
    h = rand_register_history(n_ops=30, n_processes=4, seed=41)
    e = enc_mod.encode(model, History.wrap(h))
    engine.check_encoded(e, capacity=128, dedupe="hash",
                         search_stats=True)
    snap = obs.registry().snapshot()
    assert snap["engine.search.events"]["value"] == e.n_returns
    assert snap["engine.search.frontier_peak"]["value"] > 0
    assert any(k.startswith("engine.search.probe_len.") for k in snap)
    body = httpd.render_prometheus()
    assert "jepsen_engine_search_events" in body
    assert "jepsen_engine_search_frontier_peak" in body
    assert "jepsen_engine_search_probe_len_0" in body


def test_counter_tracks_in_chrome_trace():
    tr = obs.configure(True)
    model = CASRegister()
    h = rand_register_history(n_ops=30, n_processes=4, seed=42)
    e = enc_mod.encode(model, History.wrap(h))
    r = engine.check_encoded(e, capacity=128, dedupe="hash",
                             search_stats=True)
    events = obs.chrome_trace(tr)
    cs = [ev for ev in events if ev["ph"] == "C"]
    widths = [ev["args"]["value"] for ev in cs
              if ev["name"] == "engine.search.frontier_width"]
    assert widths == r["stats"]["frontier-width"]
    lfs = [ev for ev in cs if ev["name"] == "engine.search.load_factor"]
    assert len(lfs) == len(widths)
    # samples live inside the trace's time base
    assert all(ev["ts"] >= 0 for ev in cs)


def test_counter_track_sample_cap():
    from jepsen_tpu.parallel.engine import (STATS_TRACK_MAX_SAMPLES,
                                            _emit_stats_tracks)

    obs.configure(True)
    n = 4 * STATS_TRACK_MAX_SAMPLES
    block = {"frontier-width": list(range(n)),
             "closure-peak": list(range(n)), "table-capacity": None}
    _emit_stats_tracks(block, 0.0, 1.0)
    cs = obs.tracer().counters()
    assert 0 < len(cs) <= STATS_TRACK_MAX_SAMPLES + 1


def test_breaker_and_gauge_counter_tracks():
    from jepsen_tpu.resilience.breaker import CircuitBreaker

    obs.configure(True)
    br = CircuitBreaker("testbk", threshold=2, backoff_base=1.0,
                        clock=lambda: 0.0, probe=lambda: True)
    br.record_failure("x")
    br.record_failure("x")
    names = [c[0] for c in obs.tracer().counters()]
    assert "resilience.breaker.testbk.state" in names
    # the last sample carries the OPEN state (2)
    vals = [c[2] for c in obs.tracer().counters()
            if c[0] == "resilience.breaker.testbk.state"]
    assert vals[-1] == 2


def test_export_run_and_report(tmp_path):
    from jepsen_tpu.obs import search_report

    model = CASRegister()
    hs = [rand_register_history(n_ops=24, n_processes=4, seed=800 + s)
          for s in range(3)]
    for h in hs:
        e = enc_mod.encode(model, History.wrap(h))
        engine.check_encoded(e, capacity=128, dedupe="hash",
                             search_stats=True)
    # tracing OFF: export still writes the search-stats artifact
    out = obs.export_run(str(tmp_path))
    assert out == {"search_stats": str(tmp_path / "search_stats.jsonl")}
    recs = [json.loads(ln) for ln in
            open(tmp_path / "search_stats.jsonl")]
    assert len(recs) == 3 and all("frontier-width" in r for r in recs)
    # drained: a second export with nothing new is a clean None
    assert obs.export_run(str(tmp_path)) is None
    rc = search_report.report_main(
        ["--search", "--run-dir", str(tmp_path)])
    assert rc == 0
    txt = open(tmp_path / "search_report.txt").read()
    assert "Search telemetry report" in txt
    assert "load factor" in txt
    # no stats file in an empty dir -> exit 1, not a crash
    empty = tmp_path / "empty"
    empty.mkdir()
    assert search_report.report_main(
        ["--search", "--run-dir", str(empty)]) == 1


def test_collector_keeps_newest_records():
    """Past the bound the OLDEST record drops: a streamed key's
    freshest lifetime block must survive a long soak (the report keeps
    newest-per-key)."""
    from jepsen_tpu.obs import export as export_mod

    cap = export_mod.SEARCH_STATS_MAX_RECORDS
    for i in range(cap + 7):
        obs.record_search_stats({"key": "k", "i": i})
    recs = obs.drain_search_stats()
    assert len(recs) == cap
    assert recs[-1]["i"] == cap + 6 and recs[0]["i"] == 7
    assert obs.registry().snapshot()[
        "obs.search_stats_dropped"]["value"] == 7


def test_trajectory_cap_marks_truncated():
    """Lifetime trajectories are bounded (serve keys must stay
    bounded-memory); past the cap the block says so instead of
    silently covering everything."""
    n = engine.SEARCH_STATS_MAX_EVENTS
    acc = engine.SearchStats("hash")
    chunk = {k: np.ones(n + 10, np.int32)
             for k in ("width", "peak", "iters", "stepped", "swork")}
    chunk["phist"] = np.ones((n + 10, engine.N_PROBE_BUCKETS), np.int32)
    acc.add_chunk(chunk, 64)
    b = acc.block()
    assert b["events"] == n and b["truncated"] is True
    small = engine.SearchStats("hash")
    small.add_chunk({k: v[:4] for k, v in chunk.items()}, 64)
    assert "truncated" not in small.block()


def test_status_metrics_quantiles():
    from jepsen_tpu.obs import httpd

    h = obs.histogram("serve.ack_secs")
    for v in [0.0002] * 50 + [0.02] * 5 + [0.5]:
        h.observe(v)
    obs.histogram("serve.verdict_secs").observe(0.003)
    obs.counter("serve.deltas").inc(2)
    body = httpd.render_prometheus()
    summary = httpd.render_metrics_summary(body)
    # quantiles, not raw buckets: the SLO histograms answer p50/p95/p99
    assert "jepsen_serve_ack_secs" in summary
    assert "p50" in summary and "p95" in summary and "p99" in summary
    assert 'le="' not in summary          # raw buckets stay in --raw
    assert "jepsen_serve_deltas" in summary
    # the parsed quantiles match hist_quantile over the live snapshot
    snap = obs.registry().snapshot()["serve.ack_secs"]
    parsed = httpd.parse_prometheus(body)["jepsen_serve_ack_secs"]
    for q in (0.5, 0.95, 0.99):
        assert obs.hist_quantile(parsed, q) == \
            obs.hist_quantile(snap, q)
    # past-the-ladder observations: the histogram _max twin keeps p99
    # answerable (the overloaded-SLO case), equal to the live snapshot
    for _ in range(20):
        h.observe(120.0)
    body = httpd.render_prometheus()
    assert "jepsen_serve_ack_secs_max 120" in body
    parsed = httpd.parse_prometheus(body)["jepsen_serve_ack_secs"]
    snap = obs.registry().snapshot()["serve.ack_secs"]
    assert obs.hist_quantile(parsed, 0.99) \
        == obs.hist_quantile(snap, 0.99) == 120.0


# ----------------------------------------------------- off = no-op


def test_flag_validation(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_SEARCH_STATS", "yes")
    with pytest.raises(envflags.EnvFlagError):
        engine._resolve_search_stats(None)
    monkeypatch.setenv("JEPSEN_TPU_SEARCH_STATS", "1")
    assert engine._resolve_search_stats(None) is True
    monkeypatch.setenv("JEPSEN_TPU_SEARCH_STATS", "0")
    assert engine._resolve_search_stats(None) is False
    # an explicit argument wins over the env flag
    monkeypatch.setenv("JEPSEN_TPU_SEARCH_STATS", "0")
    assert engine._resolve_search_stats(True) is True


def test_env_flag_drives_the_result(monkeypatch):
    model = CASRegister()
    h = rand_register_history(n_ops=24, n_processes=4, seed=51)
    e = enc_mod.encode(model, History.wrap(h))
    monkeypatch.setenv("JEPSEN_TPU_SEARCH_STATS", "1")
    assert "stats" in engine.check_encoded(e, capacity=128,
                                           dedupe="hash")
    monkeypatch.delenv("JEPSEN_TPU_SEARCH_STATS")
    assert "stats" not in engine.check_encoded(e, capacity=128,
                                               dedupe="hash")


def test_stats_off_is_noop_everywhere():
    """The stats-off pin: no result key, no registry series, no
    counter-track events, no run-dir records — and counter_sample with
    tracing off retains zero allocations (the PR-4 standard for
    disabled telemetry)."""
    tr = obs.configure(True)
    model = CASRegister()
    h = rand_register_history(n_ops=24, n_processes=4, seed=52)
    e = enc_mod.encode(model, History.wrap(h))
    r = engine.check_encoded(e, capacity=128, dedupe="hash")
    assert "stats" not in r
    assert not any(k.startswith("engine.search.")
                   for k in obs.registry().snapshot())
    assert not any(c[0].startswith("engine.search.")
                   for c in tr.counters())
    assert obs.search_stats_records() == []
    obs.configure(False)
    # disabled counter_sample: zero retained allocations inside the
    # tracer module (the test_obs disabled-span guard's exact method —
    # filter to the one file the call touches, so unrelated background
    # threads elsewhere in obs can't flake the pin)
    import sys
    trmod = sys.modules["jepsen_tpu.obs.tracer"]
    for _ in range(5000):          # warm past one-time interpreter
        obs.counter_sample("warmup", 1)   # call-machinery allocations
    tracemalloc.start()
    before = tracemalloc.take_snapshot()
    for _ in range(50_000):
        obs.counter_sample("pipeline.inflight", 3)
    after = tracemalloc.take_snapshot()
    tracemalloc.stop()
    flt = (tracemalloc.Filter(True, trmod.__file__),)
    growth = sum(st.size_diff for st in
                 after.filter_traces(flt).compare_to(
                     before.filter_traces(flt), "filename"))
    assert growth <= 0, \
        f"tracer retained {growth} bytes over 50k disabled samples"
