"""Frontier-sharded engine over the 8-virtual-device CPU mesh."""

import numpy as np

import jax
from jax.sharding import Mesh

from jepsen_tpu.checker import wgl
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, sharded


def _mesh():
    return Mesh(np.array(jax.devices()), ("frontier",))


def test_sharded_differential():
    mesh = _mesh()
    for seed in range(6):
        h = rand_register_history(n_ops=60, n_processes=5, crash_p=0.06,
                                  fail_p=0.06, seed=seed + 77)
        e = enc_mod.encode(CASRegister(), h)
        r = sharded.check_encoded_sharded(e, mesh, capacity=512)
        expect = wgl.analysis(CASRegister(), h)["valid?"]
        assert r["valid?"] is expect, (seed, r)
        assert r["devices"] == 8

        bad = corrupt_history(h, seed=seed)
        eb = enc_mod.encode(CASRegister(), bad)
        rb = sharded.check_encoded_sharded(eb, mesh, capacity=512)
        exb = wgl.analysis(CASRegister(), bad)["valid?"]
        assert rb["valid?"] is exb, (seed, rb, exb)


def test_sharded_counterexample():
    mesh = _mesh()
    from jepsen_tpu.history import History, invoke_op, ok_op

    h = History.wrap([
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 2),
    ]).index()
    e = enc_mod.encode(CASRegister(), h)
    r = sharded.check_encoded_sharded(e, mesh, capacity=256)
    assert r["valid?"] is False
    assert r["op"]["f"] == "read" and r["op"]["value"] == 2
