"""Frontier-sharded engine over the 8-virtual-device CPU mesh."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from jepsen_tpu.checker import wgl
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, sharded


def _mesh():
    return Mesh(np.array(jax.devices()), ("frontier",))


def test_sharded_differential():
    mesh = _mesh()
    for seed in range(6):
        h = rand_register_history(n_ops=60, n_processes=5, crash_p=0.06,
                                  fail_p=0.06, seed=seed + 77)
        e = enc_mod.encode(CASRegister(), h)
        r = sharded.check_encoded_sharded(e, mesh, capacity=512)
        expect = wgl.analysis(CASRegister(), h)["valid?"]
        assert r["valid?"] is expect, (seed, r)
        assert r["devices"] == 8

        bad = corrupt_history(h, seed=seed)
        eb = enc_mod.encode(CASRegister(), bad)
        rb = sharded.check_encoded_sharded(eb, mesh, capacity=512)
        exb = wgl.analysis(CASRegister(), bad)["valid?"]
        assert rb["valid?"] is exb, (seed, rb, exb)


def test_sharded_counterexample():
    mesh = _mesh()
    from jepsen_tpu.history import History, invoke_op, ok_op

    h = History.wrap([
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 2),
    ]).index()
    e = enc_mod.encode(CASRegister(), h)
    r = sharded.check_encoded_sharded(e, mesh, capacity=256)
    assert r["valid?"] is False
    assert r["op"]["f"] == "read" and r["op"]["value"] == 2


def _wide_frontier_history(n_crashed=10, read_value=3):
    """n_crashed concurrent crashed writes of distinct values, then one
    ok read: at the read's return the closure explores every subset of
    the crashed writes — the global frontier peaks around
    n_crashed * 2^(n_crashed-1) configs, far past one device's share of
    a small initial capacity."""
    from jepsen_tpu.history import History, invoke_op, ok_op, info_op
    ops = []
    for v in range(1, n_crashed + 1):
        ops.append(invoke_op(v, "write", v))
    for v in range(1, n_crashed + 1):
        ops.append(info_op(v, "write", v))
    ops.append(invoke_op(0, "read", None))
    ops.append(ok_op(0, "read", read_value))
    return History.wrap(ops).index()


def test_sharded_frontier_past_one_device_grows_capacity():
    """Pushes the global frontier well past one device's share of the
    starting capacity: the engine must double through several tiers
    (the same overflow policy as engine.check_encoded) and still agree
    with the host oracle. Exercises the owner-routed exchange and the
    rehash/compaction path under a deep closure (10 crashed slots ->
    ~5k configs rehashed every round)."""
    mesh = _mesh()
    h = _wide_frontier_history(n_crashed=10, read_value=3)
    e = enc_mod.encode(CASRegister(), h)
    r = sharded.check_encoded_sharded(e, mesh, capacity=512)
    expect = wgl.analysis(CASRegister(), h)["valid?"]
    assert r["valid?"] is expect is True
    assert r["capacity"] > 512, "expected capacity growth"
    # the peak global frontier would not fit on any single device's
    # share — sharding, not padding, is what made this run
    assert r["max-frontier"] > r["capacity"] // r["devices"], r

    # invalid variant: a read of a never-written value must fail at the
    # same wide-closure event
    hb = _wide_frontier_history(n_crashed=10, read_value=99)
    eb = enc_mod.encode(CASRegister(), hb)
    rb = sharded.check_encoded_sharded(eb, mesh, capacity=512)
    assert rb["valid?"] is False
    assert rb["op"]["f"] == "read" and rb["op"]["value"] == 99


_PIN_KEYS = ("valid?", "op", "fail-event", "max-frontier", "capacity")


def _pin(r):
    return {k: r.get(k) for k in _PIN_KEYS}


def test_sharded_hash_dedupe_parity():
    """dedupe="hash" (per-device open-addressed visited sets, delta
    expansion) vs the sort path on the 8-way mesh: identical verdict,
    localization, max-frontier and capacity on clean + corrupted
    histories, with the configs-stepped counter showing the delta
    doing LESS work; the 2-D hierarchical topology must agree with the
    flat mesh under hash too. (The deep-closure capacity-growth case
    is the slow-marked companion below.)"""
    mesh = _mesh()
    h = rand_register_history(n_ops=50, n_processes=5, crash_p=0.06,
                              fail_p=0.06, seed=81)
    for hv in (h, corrupt_history(h, seed=4)):
        e = enc_mod.encode(CASRegister(), hv)
        rs = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                           dedupe="sort")
        rh = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                           dedupe="hash")
        assert _pin(rs) == _pin(rh), (rs, rh)
        assert rh["configs-stepped"] <= rs["configs-stepped"]
        assert rh["dedupe"] == "hash" and rs["dedupe"] == "sort"

    # 2-D hierarchical topology, same pins
    devs = np.array(jax.devices()).reshape(2, 4)
    mesh2d = Mesh(devs, ("slice", "chip"))
    e = enc_mod.encode(CASRegister(), h)
    r2h = sharded.check_encoded_sharded(e, mesh2d, capacity=512,
                                        dedupe="hash")
    r1h = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                        dedupe="hash")
    assert _pin(r2h) == _pin(r1h), (r2h, r1h)
    assert "hierarchical" in r2h.get("mesh", "")


@pytest.mark.slow
def test_sharded_hash_dedupe_parity_capacity_growth():
    """Deep closure + capacity growth under dedupe="hash": the delta
    win shows (strictly fewer configs stepped) and the escalation
    tiers land identically to sort. slow-marked: the wide-frontier
    search pays several capacity-tier shard_map compiles."""
    mesh = _mesh()
    hw = _wide_frontier_history(n_crashed=9, read_value=3)
    ew = enc_mod.encode(CASRegister(), hw)
    ws = sharded.check_encoded_sharded(ew, mesh, capacity=512,
                                       dedupe="sort")
    wh = sharded.check_encoded_sharded(ew, mesh, capacity=512,
                                       dedupe="hash")
    assert _pin(ws) == _pin(wh) and ws["valid?"] is True, (ws, wh)
    assert ws["capacity"] > 512
    assert wh["configs-stepped"] < ws["configs-stepped"], (ws, wh)


def test_sharded_route_and_gather_agree():
    """The owner-routed all-to-all exchange and the broadcast all-gather
    exchange are two implementations of the same global dedupe — they
    must produce identical results and frontier statistics."""
    mesh = _mesh()
    h = _wide_frontier_history(n_crashed=8, read_value=2)
    e = enc_mod.encode(CASRegister(), h)
    r_route = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                            exchange="route")
    r_gather = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                             exchange="gather")
    assert r_route == r_gather, (r_route, r_gather)


@pytest.mark.slow
def test_sharded_hierarchical_2d_mesh():
    """A 2-D mesh (slice x chip) routes hierarchically — intra-slice
    all-to-all then inter-slice all-to-all — and must agree exactly
    with the flat 1-D route and the host oracle, including under
    capacity growth with the frontier past one device's share.

    slow-marked: two mesh shapes x several shard_map compiles ≈ 80s+
    on the 2-core CI box (unrunnable before the jax-version shim, so
    tier-1 never carried it); the 2-D topology keeps fast tier-1
    coverage via test_sharded_hash_dedupe_parity's 2x4 case."""
    devs = np.array(jax.devices())
    for shape in ((2, 4), (4, 2)):
        mesh2d = Mesh(devs.reshape(shape), ("slice", "chip"))
        for seed in (3, 5):
            h = rand_register_history(n_ops=50, n_processes=5,
                                      crash_p=0.06, fail_p=0.06,
                                      seed=seed + 300)
            e = enc_mod.encode(CASRegister(), h)
            r2d = sharded.check_encoded_sharded(e, mesh2d, capacity=512)
            r1d = sharded.check_encoded_sharded(e, _mesh(), capacity=512)
            expect = wgl.analysis(CASRegister(), h)["valid?"]
            assert r2d["valid?"] is r1d["valid?"] is expect, \
                (shape, seed, r2d, r1d)
            assert r2d["devices"] == 8
            assert "hierarchical" in r2d.get("mesh", ""), r2d

        # wide frontier: growth + cross-slice traffic under load
        hw = _wide_frontier_history(n_crashed=10, read_value=3)
        ew = enc_mod.encode(CASRegister(), hw)
        rw = sharded.check_encoded_sharded(ew, mesh2d, capacity=512)
        assert rw["valid?"] is True and rw["capacity"] > 512, rw
        assert rw["max-frontier"] > rw["capacity"] // 8, rw

        # invalid localization across slices
        hb = _wide_frontier_history(n_crashed=8, read_value=99)
        eb = enc_mod.encode(CASRegister(), hb)
        rb = sharded.check_encoded_sharded(eb, mesh2d, capacity=512)
        assert rb["valid?"] is False and rb["op"]["value"] == 99, rb


@pytest.mark.slow
def test_sharded_1k_invalid_end_to_end():
    """A >=1k-op invalid history checked end-to-end on the 8-device
    mesh, counterexample included (the VERDICT r2 ask: multi-chip
    correctness must not rest on 16-48-op smoke histories).

    slow-marked: a 1k-op, 8-virtual-device search is minutes of wall
    on the 2-core CI box — exactly the "large adversarial histories"
    class the marker exists for. (It was unrunnable before the
    jax-version shard_map shim, so tier-1 never carried its cost.)"""
    h = rand_register_history(n_ops=1000, n_processes=6, crash_p=0.005,
                              fail_p=0.03, n_values=5, seed=2026)
    ops = [dict(o) for o in h]
    n = len(ops)
    ops += [{"index": n, "time": ops[-1]["time"] + 1, "process": 95,
             "type": "invoke", "f": "read", "value": None},
            {"index": n + 1, "time": ops[-1]["time"] + 2, "process": 95,
             "type": "ok", "f": "read", "value": "never-written"}]
    from jepsen_tpu.history import History
    hb = History.wrap(ops).index()
    r = sharded.analysis(CASRegister(), hb, _mesh(), capacity=1024)
    assert r["valid?"] is False
    assert r["op"]["value"] == "never-written"
    assert r["devices"] == 8
    assert r["final-paths"], r.get("final-paths-note")
