"""The driver's multi-chip dryrun must never touch the default backend.

MULTICHIP_r01 regression: the dryrun deliberately runs on a virtual CPU
mesh, but array creation (jnp.asarray) landed on the *default* backend —
so any TPU-runtime breakage (libtpu version mismatch, driver flake)
crashed a CPU-mesh dryrun. The fix pins everything: jax.default_device
around the dryrun body plus explicit device_put of every batch onto the
mesh (engine.encode_batch / encode.place_batch / sharded's replicated
_xs_from_encoded).

These tests simulate an unusable default backend in a subprocess: 9
virtual CPU devices, the mesh built from devices 1..8, and every array-
creation entry point (jnp.asarray / jnp.array / jnp.int32 / uint32 /
jax.device_put) patched to raise the moment a result lands on the
poisoned default device 0.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

POISON_PRELUDE = """
import os, sys
sys.path.insert(0, {repo!r})
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=9"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp

devs = jax.devices()
assert len(devs) == 9, devs
POISONED = devs[0]          # the process-wide default device

class DefaultBackendTouched(Exception):
    pass

def _guard(fn, name):
    def wrapped(*a, **k):
        out = fn(*a, **k)
        try:
            on_poisoned = isinstance(out, jax.Array) \\
                and POISONED in out.devices()
        except Exception:
            on_poisoned = False
        if on_poisoned:
            raise DefaultBackendTouched(
                name + " placed an array on the poisoned default device")
        return out
    return wrapped

# NB: jnp.int32/uint32 double as dtype objects (dtype=jnp.int32), so the
# scalar-constructor path can't be wrapped; jnp.asarray / jnp.array /
# device_put cover every host->device batch entry point in the engine.
jnp.asarray = _guard(jnp.asarray, "jnp.asarray")
jnp.array = _guard(jnp.array, "jnp.array")
jax.device_put = _guard(jax.device_put, "jax.device_put")
"""


def _run(body: str) -> subprocess.CompletedProcess:
    code = POISON_PRELUDE.format(repo=REPO) + body
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    return subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=600)


@pytest.mark.slow
def test_dryrun_with_poisoned_default_backend():
    """__graft_entry__._dryrun_on_devices(devs[1:9]) completes even when
    any placement on the default device raises."""
    r = _run("""
import __graft_entry__
__graft_entry__._dryrun_on_devices(devs[1:9])
print("DRYRUN_OK")
""")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "DRYRUN_OK" in r.stdout


@pytest.mark.slow
def test_poison_guard_actually_fires():
    """Sanity: the guard in the subprocess does reject default-device
    placement — otherwise the test above proves nothing."""
    r = _run("""
try:
    jnp.asarray([1, 2, 3])
except DefaultBackendTouched:
    print("GUARD_FIRED")
""")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "GUARD_FIRED" in r.stdout


@pytest.mark.slow
def test_engine_paths_pin_to_mesh_with_poisoned_default():
    """check_batch(mesh=...) — both divisible and non-divisible key
    counts — and check_encoded_sharded place everything on the mesh."""
    r = _run("""
import numpy as np
from jax.sharding import Mesh
from jepsen_tpu.histories import rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, engine, sharded

mesh = Mesh(np.array(devs[1:9]), ("keys",))
with jax.default_device(devs[1]):
    hs = [rand_register_history(n_ops=16, n_processes=3, crash_p=0.0,
                                seed=s) for s in range(8)]
    rs = engine.check_batch(CASRegister(), hs, capacity=128, mesh=mesh)
    assert all(r["valid?"] is True for r in rs), rs
    # non-divisible K (5 keys over 8 devices) -> replicated placement
    rs = engine.check_batch(CASRegister(), hs[:5], capacity=128, mesh=mesh)
    assert all(r["valid?"] is True for r in rs), rs
    e = enc_mod.encode(CASRegister(),
                       rand_register_history(n_ops=48, n_processes=4,
                                             crash_p=0.03, fail_p=0.05,
                                             seed=5))
    r = sharded.check_encoded_sharded(e, mesh, capacity=64 * 8)
    assert r["valid?"] is True, r
print("ENGINE_OK")
""")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ENGINE_OK" in r.stdout
