import numpy as np

from jepsen_tpu.history import (
    Columns, History, Op, calls, invoke_op, ok_op, fail_op, info_op,
)


def _h(*ops):
    return History.wrap(ops).index()


def test_op_attr_access():
    o = Op(type="invoke", process=0, f="read", value=None)
    assert o.type == "invoke"
    assert o.f == "read"
    assert o.value is None
    assert o.is_invoke
    o.value = 3
    assert o["value"] == 3


def test_index_and_pairs():
    h = _h(
        invoke_op(0, "write", 1),
        invoke_op(1, "read", None),
        ok_op(0, "write", 1),
        ok_op(1, "read", 1),
    )
    h.pairs()
    assert h[0]["pair-index"] == 2
    assert h[2]["pair-index"] == 0
    assert h[1]["pair-index"] == 3


def test_complete_fills_read_values():
    h = _h(
        invoke_op(0, "read", None),
        ok_op(0, "read", 7),
    ).complete()
    assert h[0]["value"] == 7


def test_calls_pairing():
    h = _h(
        invoke_op(0, "write", 1),
        invoke_op(1, "read", None),
        ok_op(0, "write", 1),
        info_op(1, "read", None),       # crashed
        invoke_op(2, "cas", [1, 2]),
        fail_op(2, "cas", [1, 2]),      # failed: dropped
    )
    cs = calls(h)
    assert len(cs) == 2
    w, r = cs
    assert w.f == "write" and not w.crashed and w.complete_index == 2
    assert r.f == "read" and r.crashed and r.complete_index == len(h)


def test_edn_round_trip():
    h = _h(
        invoke_op(0, "write", 1, time=10),
        ok_op(0, "write", 1, time=20),
        info_op("nemesis", "start", None, time=30),
    )
    text = h.to_edn()
    h2 = History.from_edn(text)
    assert len(h2) == 3
    assert h2[0]["type"] == "invoke"
    assert h2[0]["process"] == 0
    assert h2[2]["process"] == "nemesis"


def test_columns():
    h = _h(
        invoke_op(0, "write", 5, time=1),
        ok_op(0, "write", 5, time=2),
        invoke_op("nemesis", "start", None, time=3),
    )
    c = Columns.from_history(h)
    assert len(c) == 3
    assert c.process[2] == -2
    assert c.type[0] == 0 and c.type[1] == 1
    assert c.f_table.value(c.f[0]) == "write"
    assert c.value_table.value(c.value[0]) == 5
    assert c.value[2] == -1
    assert c.index.dtype == np.int64


def test_calls_keep_failed():
    h = _h(
        invoke_op(0, "write", 1),
        fail_op(0, "write", 1),
    )
    assert calls(h) == []
    kept = calls(h, drop_failed=False)
    assert len(kept) == 1 and kept[0].complete_index == 1


# ---------------------------------------------------- npz sidecar

def test_npz_roundtrip_exact_plain():
    """Typical checker history: reconstructs fully from columns (zero
    override lines) and round-trips exactly."""
    import numpy as np
    from jepsen_tpu.histories import rand_register_history

    h = rand_register_history(n_ops=300, n_processes=5, crash_p=0.02,
                              fail_p=0.05, seed=4)
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = h.save_npz(os.path.join(d, "history"))
        assert p.endswith(".npz")
        z = np.load(p, allow_pickle=False)
        # only ops the columns can't express (here: crashed ops with an
        # "error" key) may need an override line; the bulk must be
        # purely columnar
        n_err = sum(1 for o in h if set(o) - {
            "index", "time", "process", "type", "f", "value"})
        assert len(z["override_idx"]) == n_err < len(h) // 10
        h2 = History.load_npz(p)
    assert len(h2) == len(h)
    assert [dict(a) for a in h2] == [dict(b) for b in h]


def test_npz_roundtrip_exact_weird_ops():
    """Ops the columns cannot express — extra keys, non-int non-nemesis
    process, unknown type, tuple values — ride as EDN overrides and
    still round-trip exactly."""
    from jepsen_tpu.history import NEMESIS

    h = History.wrap([
        {"index": 0, "time": 3, "process": 0, "type": "invoke",
         "f": "write", "value": 3},
        {"index": 1, "time": 4, "process": 0, "type": "ok",
         "f": "write", "value": 3, "node": "n1", "error": ["timed-out"]},
        {"index": 2, "process": NEMESIS, "type": "info",
         "f": "start-partition", "value": ["n1", "n2"]},
        {"index": 3, "process": 1, "type": "invoke", "f": "cas",
         "value": [1, 2]},
        {"index": 4, "process": 1, "type": "fail", "f": "cas",
         "value": [1, 2]},
        {"index": 5, "process": 2, "type": "invoke", "f": "read"},
    ])
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        p = h.save_npz(os.path.join(d, "history.npz"))
        h2 = History.load_npz(p)
    assert [dict(a) for a in h2] == [dict(b) for b in h]


def test_store_writes_and_prefers_npz(tmp_path, monkeypatch):
    """save_1 writes the sidecar next to history.edn; load_run prefers
    it (the EDN is not even parsed), falling back loudly when the
    sidecar is corrupt."""
    from jepsen_tpu import store as store_mod
    from jepsen_tpu.histories import rand_register_history
    import os

    h = rand_register_history(n_ops=50, n_processes=3, crash_p=0.01,
                              fail_p=0.05, seed=8)
    st = store_mod.Store("npz-test", base_dir=str(tmp_path))
    st.save_1({"name": "npz-test"}, h)
    assert os.path.exists(st.path("history.npz"))

    # poison the EDN: a parse would now blow up, proving npz is used
    # (bump the sidecar's mtime past the rewrite so it is not treated
    # as stale)
    with open(st.path("history.edn"), "w") as fh:
        fh.write("{:broken")
    os.utime(st.path("history.npz"))
    run = store_mod.load_run(st.dir)
    assert [dict(a) for a in run["history"]] == [dict(b) for b in h]

    # a history.edn rewritten AFTER the sidecar (hand-corrected replay)
    # must win: the stale sidecar is skipped, loudly
    import time as _t
    h_fixed = rand_register_history(n_ops=20, n_processes=3,
                                    crash_p=0.0, fail_p=0.0, seed=99)
    _t.sleep(0.02)
    h_fixed.save(st.path("history.edn"))
    run = store_mod.load_run(st.dir)
    assert [dict(a) for a in run["history"]] == [dict(b) for b in h_fixed]

    # corrupt sidecar: loud fallback to EDN (restore it first)
    h.save(st.path("history.edn"))
    with open(st.path("history.npz"), "wb") as fh:
        fh.write(b"not-an-npz")
    run = store_mod.load_run(st.dir)
    assert [dict(a) for a in run["history"]] == [dict(b) for b in h]
