import numpy as np

from jepsen_tpu.history import (
    Columns, History, Op, calls, invoke_op, ok_op, fail_op, info_op,
)


def _h(*ops):
    return History.wrap(ops).index()


def test_op_attr_access():
    o = Op(type="invoke", process=0, f="read", value=None)
    assert o.type == "invoke"
    assert o.f == "read"
    assert o.value is None
    assert o.is_invoke
    o.value = 3
    assert o["value"] == 3


def test_index_and_pairs():
    h = _h(
        invoke_op(0, "write", 1),
        invoke_op(1, "read", None),
        ok_op(0, "write", 1),
        ok_op(1, "read", 1),
    )
    h.pairs()
    assert h[0]["pair-index"] == 2
    assert h[2]["pair-index"] == 0
    assert h[1]["pair-index"] == 3


def test_complete_fills_read_values():
    h = _h(
        invoke_op(0, "read", None),
        ok_op(0, "read", 7),
    ).complete()
    assert h[0]["value"] == 7


def test_calls_pairing():
    h = _h(
        invoke_op(0, "write", 1),
        invoke_op(1, "read", None),
        ok_op(0, "write", 1),
        info_op(1, "read", None),       # crashed
        invoke_op(2, "cas", [1, 2]),
        fail_op(2, "cas", [1, 2]),      # failed: dropped
    )
    cs = calls(h)
    assert len(cs) == 2
    w, r = cs
    assert w.f == "write" and not w.crashed and w.complete_index == 2
    assert r.f == "read" and r.crashed and r.complete_index == len(h)


def test_edn_round_trip():
    h = _h(
        invoke_op(0, "write", 1, time=10),
        ok_op(0, "write", 1, time=20),
        info_op("nemesis", "start", None, time=30),
    )
    text = h.to_edn()
    h2 = History.from_edn(text)
    assert len(h2) == 3
    assert h2[0]["type"] == "invoke"
    assert h2[0]["process"] == 0
    assert h2[2]["process"] == "nemesis"


def test_columns():
    h = _h(
        invoke_op(0, "write", 5, time=1),
        ok_op(0, "write", 5, time=2),
        invoke_op("nemesis", "start", None, time=3),
    )
    c = Columns.from_history(h)
    assert len(c) == 3
    assert c.process[2] == -2
    assert c.type[0] == 0 and c.type[1] == 1
    assert c.f_table.value(c.f[0]) == "write"
    assert c.value_table.value(c.value[0]) == 5
    assert c.value[2] == -1
    assert c.index.dtype == np.int64


def test_calls_keep_failed():
    h = _h(
        invoke_op(0, "write", 1),
        fail_op(0, "write", 1),
    )
    assert calls(h) == []
    kept = calls(h, drop_failed=False)
    assert len(kept) == 1 and kept[0].complete_index == 1
