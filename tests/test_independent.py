"""Per-key independence tests (reference:
jepsen/test/jepsen/independent_test.clj + generator_test.clj:386-454)."""

import jepsen_tpu.generator as gen
from jepsen_tpu import independent
from jepsen_tpu.checker import linearizable
from jepsen_tpu.checker.core import FnChecker
from jepsen_tpu.generator.testing import default_context, perfect, quick
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.independent import KV
from jepsen_tpu.models import CASRegister


def test_ktuple():
    t = KV("x", 5)
    assert t.key == "x" and t.value == 5
    assert tuple(t) == ("x", 5)
    assert independent.is_tuple(t)
    assert not independent.is_tuple(("x", 5))


def test_sequential_generator():
    g = independent.sequential_generator(
        [0, 1], lambda k: gen.limit(2, lambda: {"f": "read", "value": None}))
    h = quick(g)
    vals = [o["value"] for o in h]
    assert vals == [KV(0, None), KV(0, None), KV(1, None), KV(1, None)]


def test_concurrent_generator_covers_keys_in_order():
    ctx = default_context(4)  # 4 workers -> 2 groups of 2
    g = independent.concurrent_generator(
        2, ["a", "b", "c", "d"],
        lambda k: gen.limit(3, lambda: {"f": "w", "value": 1}))
    h = perfect(g, ctx)
    keys = [o["value"].key for o in h]
    assert len(h) == 12  # 4 keys x 3 ops
    # first two keys are worked concurrently by distinct groups
    first_half = set(keys[:6])
    assert first_half == {"a", "b"}
    # threads stay within their group per key
    by_key = {}
    for o in h:
        by_key.setdefault(o["value"].key, set()).add(o["process"] % 4)
    for k, procs in by_key.items():
        assert procs <= {0, 1} or procs <= {2, 3}, (k, procs)


def test_history_keys_and_subhistory():
    h = History.wrap([
        invoke_op(0, "write", KV("x", 1)),
        invoke_op("nemesis", "kill", None),
        ok_op(0, "write", KV("x", 1)),
        invoke_op(1, "read", KV("y", None)),
        ok_op(1, "read", KV("y", 7)),
    ])
    assert independent.history_keys(h) == ["x", "y"]
    hx = independent.subhistory("x", h)
    assert [o.get("f") for o in hx] == ["write", "kill", "write"]
    assert hx[0]["value"] == 1  # unwrapped
    hy = independent.subhistory("y", h)
    assert [o.get("value") for o in hy if o["f"] == "read"] == [None, 7]


def test_kv_history_reinterprets_vectors():
    h = History.wrap([invoke_op(0, "w", [3, 9]), ok_op(0, "w", [3, 9])])
    h2 = independent.kv_history(h)
    assert independent.history_keys(h2) == [3]


def _keyed_register_history():
    """Two keys: x linearizable, y not (read 5 never written)."""
    ops = [
        invoke_op(0, "write", KV("x", 1)), ok_op(0, "write", KV("x", 1)),
        invoke_op(0, "read", KV("x", None)), ok_op(0, "read", KV("x", 1)),
        invoke_op(1, "write", KV("y", 2)), ok_op(1, "write", KV("y", 2)),
        invoke_op(1, "read", KV("y", None)), ok_op(1, "read", KV("y", 5)),
    ]
    return History.wrap(ops).index()


def test_independent_checker_host():
    c = independent.checker(linearizable(CASRegister(), algorithm="wgl"))
    r = c.check({}, _keyed_register_history())
    assert r["valid?"] is False
    assert r["results"]["x"]["valid?"] is True
    assert r["results"]["y"]["valid?"] is False
    assert r["failures"] == ["y"]


def test_independent_checker_device_batch():
    c = independent.checker(linearizable(CASRegister(), algorithm="jax"))
    r = c.check({}, _keyed_register_history())
    assert r["valid?"] is False
    assert r["failures"] == ["y"]
    assert r["results"]["x"]["analyzer"] == "jax"


def test_independent_checker_device_batch_with_mesh():
    """test["mesh"] shards the per-key batch over the device mesh (the
    dp axis) and arms the sharded-escalation path for overflow keys."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:8]), ("keys",))
    c = independent.checker(linearizable(CASRegister(), algorithm="jax"))
    r = c.check({"mesh": mesh}, _keyed_register_history())
    assert r["valid?"] is False
    assert r["failures"] == ["y"]
    assert r["results"]["x"]["analyzer"] == "jax"

    # a Mesh on the test map must be stripped before serialization
    from jepsen_tpu.store import serializable_test
    assert "mesh" not in serializable_test({"mesh": mesh, "name": "t"})


def test_device_batch_failure_is_loud(monkeypatch, caplog):
    """A broken device path must not silently degrade to the host
    checker: the result carries a device-fallback tag and a warning is
    logged (the host still produces correct per-key results)."""
    import logging

    from jepsen_tpu.parallel import engine

    def boom(*a, **k):
        raise RuntimeError("simulated TPU runtime breakage")

    monkeypatch.setattr(engine, "check_batch", boom)
    c = independent.checker(linearizable(CASRegister(), algorithm="jax"))
    with caplog.at_level(logging.WARNING, logger="jepsen_tpu.independent"):
        r = c.check({}, _keyed_register_history())
    assert r["valid?"] is False          # host path still checked keys
    assert r["failures"] == ["y"]
    assert "simulated TPU runtime breakage" in r["device-fallback"]
    assert any("FAILED" in rec.message for rec in caplog.records)


def test_device_batch_not_applicable_is_quiet():
    """A host-only checker never gets the fallback tag — 'not
    applicable' is not a failure."""
    c = independent.checker(linearizable(CASRegister(), algorithm="wgl"))
    r = c.check({}, _keyed_register_history())
    assert r["valid?"] is False
    assert "device-fallback" not in r


def test_independent_checker_plain_fn():
    seen = []

    def f(test, history, opts):
        seen.append(opts.get("history-key"))
        return {"valid?": True, "n": len(history)}

    c = independent.checker(FnChecker(f))
    r = c.check({}, _keyed_register_history())
    assert r["valid?"] is True
    assert sorted(seen) == ["x", "y"]
