"""Delta-frontier closure + hash visited-set (JEPSEN_TPU_DEDUPE=hash)
vs the sort-dedupe path: verdict/counterexample/statistics parity, the
configs-stepped work reduction, probe-overflow capacity escalation, and
the flag/checkpoint plumbing. The deep six-family sweep (incl. the
sharded-mesh case) lives in the fuzz tier (test_fuzz_differential);
this file is the fast always-on pin."""

import os
import unittest.mock as mock

import numpy as np
import pytest

from jepsen_tpu.histories import (adversarial_register_history,
                                  corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import encode as enc_mod, engine

# Everything order-independent in a sparse result must MATCH between
# strategies: verdict, failing op + event, max-frontier, capacity, and
# the historical explored metric (iteration counts are identical — the
# delta closure converges in exactly the sort closure's iterations).
# Only the frontier ROW ORDER and configs-stepped may differ.
PIN = ("valid?", "op", "fail-event", "max-frontier", "capacity",
       "explored")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _parity(e, capacity=128, max_capacity=4096):
    rs = engine.check_encoded(e, capacity=capacity,
                              max_capacity=max_capacity, dedupe="sort")
    rh = engine.check_encoded(e, capacity=capacity,
                              max_capacity=max_capacity, dedupe="hash")
    assert _pin(rs) == _pin(rh), (rs, rh)
    if rs["valid?"] != "unknown":
        assert rh["configs-stepped"] <= rs["configs-stepped"], (rs, rh)
        assert rh["dedupe"] == "hash" and rs["dedupe"] == "sort"
    return rs, rh


FAMILIES = [
    ("cas-register", CASRegister,
     lambda: rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31)),
    # (plain Register shares the "register" device step with
    # CASRegister — the fuzz tier covers it; no extra compile here)
    ("gset", GSet,
     lambda: rand_gset_history(n_ops=36, n_processes=4, n_elements=9,
                               crash_p=0.06, seed=33)),
    ("uqueue", UnorderedQueue,
     lambda: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                crash_p=0.06, seed=34)),
    ("fifo", FIFOQueue,
     lambda: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                               crash_p=0.05, seed=35)),
]


@pytest.mark.parametrize("name,Model,gen", FAMILIES,
                         ids=[c[0] for c in FAMILIES])
def test_hash_parity_clean_and_corrupted(name, Model, gen):
    h = gen()
    for variant in (h, corrupt_history(h, seed=7, n_corruptions=2)):
        try:
            e = enc_mod.encode(Model(), variant)
        except enc_mod.EncodeError:
            continue  # family/shape not device-encodable: nothing to pin
        _parity(e)


def test_hash_parity_mutex_invalid():
    # mutex has no corruptible values; a double-acquire is the invalid
    # case, localized identically by both strategies
    h = History.wrap([
        invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
        invoke_op(1, "acquire", None), ok_op(1, "acquire", None),
    ]).index()
    e = enc_mod.encode(Mutex(), h)
    rs, rh = _parity(e, capacity=64, max_capacity=256)
    assert rs["valid?"] is False


def test_hash_steps_strictly_fewer_on_adversarial():
    """The acceptance shape: on an adversarial history (deep closures
    over held-open crashed writes) the delta-frontier path must pay
    STRICTLY less closure work — the settled majority stops being
    re-stepped. Pinned via the configs-stepped counters."""
    h = adversarial_register_history(n_ops=120, k_crashed=6, seed=7)
    e = enc_mod.encode(CASRegister(), h)
    # capacity sized to the peak (~10*2^(k-1)) so neither strategy pays
    # the escalation ladder's extra compiles in this fast tier
    rs, rh = _parity(e, capacity=1024, max_capacity=4096)
    assert rs["valid?"] is True
    assert rh["configs-stepped"] < rs["configs-stepped"], (rs, rh)


def test_probe_overflow_escalates_capacity_not_verdict():
    """Probe exhaustion in the visited set must degrade into the
    existing capacity-escalation retry (bigger table = lower load
    factor), never a wrong verdict or a dropped config. probe_limit=1
    makes every collision an exhaustion — the check still lands the
    sort verdict, at a (possibly) higher tier."""
    h = rand_register_history(n_ops=50, n_processes=5, n_values=4,
                              crash_p=0.05, fail_p=0.05, seed=11)
    e = enc_mod.encode(CASRegister(), h)
    ref = engine.check_encoded(e, capacity=64, dedupe="sort")
    r1 = engine.check_encoded(e, capacity=64, max_capacity=1 << 14,
                              dedupe="hash", probe_limit=1)
    assert r1["valid?"] == ref["valid?"]
    assert r1.get("op") == ref.get("op")
    assert r1["capacity"] >= ref["capacity"]


def test_frontier_overflow_same_unknown_as_sort():
    # m concurrent writes -> ~m * 2^(m-1) configs: blows every tier
    ops = []
    for p in range(26):
        ops.append(invoke_op(p, "write", 1000 + p))
    for p in range(26):
        ops.append(ok_op(p, "write", 1000 + p))
    e = enc_mod.encode(CASRegister(), History.wrap(ops).index())
    for strat in ("sort", "hash"):
        r = engine.check_encoded(e, capacity=64, max_capacity=256,
                                 dedupe=strat)
        assert r["valid?"] == "unknown" and "overflow" in r["error"], r
        assert r["dedupe"] == strat


def test_env_flag_resolution_and_validation():
    from jepsen_tpu.envflags import EnvFlagError
    assert engine._resolve_dedupe(None) == "sort"   # the default
    assert engine._resolve_dedupe("hash") == "hash"
    with pytest.raises(ValueError, match="dedupe"):
        engine._resolve_dedupe("bogus")
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_DEDUPE": "hash"}):
        assert engine._resolve_dedupe(None) == "hash"
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_DEDUPE": "bogus"}), \
            pytest.raises(EnvFlagError, match="dedupe strategy"):
        engine._resolve_dedupe(None)
    # the flag actually reaches the engine: a check under the env flag
    # reports the strategy it ran
    h = rand_register_history(n_ops=24, n_processes=3, crash_p=0.0,
                              seed=5)
    e = enc_mod.encode(CASRegister(), h)
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_DEDUPE": "hash"}):
        r = engine.check_encoded(e, capacity=64)
    assert r["dedupe"] == "hash" and r["valid?"] is True


def test_resumable_hash_matches_oneshot_and_checkpoints_stepped():
    h = rand_register_history(n_ops=120, n_processes=6, n_values=4,
                              crash_p=0.01, fail_p=0.05, busy=0.7,
                              seed=10)
    e = enc_mod.encode(CASRegister(), h)
    ref = engine.check_encoded(e, capacity=256, dedupe="hash")
    res = engine.check_encoded_resumable(e, capacity=256,
                                         checkpoint_every=16,
                                         dedupe="hash")
    assert res["valid?"] == ref["valid?"]
    assert res["max-frontier"] == ref["max-frontier"]
    assert res["configs-stepped"] == ref["configs-stepped"]
    assert res["dedupe"] == "hash"


def test_checkpoint_v1_files_load_with_zero_stepped(tmp_path):
    """FrontierCheckpoint format versioning: v2 saves carry the
    configs-stepped counter; a v1 file (6 meta scalars, written by
    prior rounds) must still load and resume — the counter is
    advisory, the search state is complete without it."""
    h = rand_register_history(n_ops=60, n_processes=4, crash_p=0.02,
                              fail_p=0.05, seed=3)
    e = enc_mod.encode(CASRegister(), h)
    cps = []
    ref = engine.check_encoded_resumable(e, capacity=64,
                                         checkpoint_every=8,
                                         dedupe="hash",
                                         checkpoint_cb=cps.append)
    cp = cps[0]
    assert cp.stepped > 0
    # v2 roundtrip keeps the counter
    p = cp.save(str(tmp_path / "v2"))
    assert engine.FrontierCheckpoint.load(p).stepped == cp.stepped
    # hand-write a v1 file: meta truncated to the 6 legacy scalars
    v1 = str(tmp_path / "v1.npz")
    np.savez_compressed(
        v1, st=cp.st, ml=cp.ml, mh=cp.mh, live=cp.live,
        meta=np.array([cp.event_index, cp.capacity, int(cp.ok),
                       cp.fail_r, cp.maxf, cp.steps_n], np.int64),
        step_name=np.array(cp.step_name),
        history_digest=np.array(cp.history_digest))
    lo = engine.FrontierCheckpoint.load(v1)
    assert lo.stepped == 0 and lo.event_index == cp.event_index
    res = engine.check_encoded_resumable(e, resume=lo, dedupe="hash")
    assert res["valid?"] == ref["valid?"]


def test_batch_and_pipeline_thread_the_strategy():
    """check_batch(dedupe=...) must reach the sparse buckets (results
    tagged, verdicts identical to sort) in both the serial and the
    pipelined executor; bitdense buckets report dedupe="dense". The
    state-rich FIFO keys route sparse, the register keys bitdense."""
    regs = [rand_register_history(n_ops=24, n_processes=3, crash_p=0.02,
                                  seed=600 + s) for s in range(3)]
    fifo = rand_fifo_history(n_ops=36, n_processes=6, n_values=3,
                             crash_p=0.15, seed=5)

    rs = engine.check_batch(CASRegister(), regs, capacity=64,
                            max_capacity=2048, dedupe="hash")
    assert all(r["dedupe"] == "dense" for r in rs), rs

    pre = [enc_mod.encode(FIFOQueue(), fifo)]
    r_sort = engine._check_batch_sparse(FIFOQueue(), pre, 128, 2048,
                                        dedupe="sort")[0]
    r_hash = engine._check_batch_sparse(FIFOQueue(), pre, 128, 2048,
                                        dedupe="hash")[0]
    assert r_sort["valid?"] == r_hash["valid?"]
    assert r_sort["max-frontier"] == r_hash["max-frontier"]
    assert r_hash["configs-stepped"] <= r_sort["configs-stepped"]
    assert r_hash["dedupe"] == "hash"

    # pipelined executor: strategy recorded in stats, sparse results
    # identical to the serial path under the same strategy
    stats = {}
    rs_p = engine.check_batch(FIFOQueue(), [fifo], capacity=128,
                              max_capacity=2048, pipeline=True,
                              cache=False, pipeline_stats=stats,
                              dedupe="hash")
    assert stats["dedupe"] == "hash"
    assert rs_p[0] == r_hash, (rs_p[0], r_hash)

    with pytest.raises(ValueError, match="dedupe"):
        engine.check_batch(CASRegister(), [], dedupe="bogus")
