"""Tests for the `jepsen-tpu lint` static-analysis pass
(jepsen_tpu.analysis).

Three layers:
  * fixture files with known violations per rule family, asserting
    exact file:line anchors (tests/data/lint_fixtures/ — parsed, never
    imported);
  * the suppression contract: comments are honored, still REPORTED
    (marked suppressed), and must carry a known rule name;
  * the repo-wide gate: `python -m jepsen_tpu.analysis --check` exits
    0 on this tree (every finding fixed or suppressed-with-rule) —
    the tier-1 entry for the lint pass.

The pass is pure-AST: no JAX import, no device init — the subprocess
test below pins that too (it must be fast even where a device runtime
would hang).
"""

import json
import os
import subprocess
import sys

from jepsen_tpu import analysis
from jepsen_tpu.analysis import core as lint_core

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "data", "lint_fixtures")


def _lint(name):
    return analysis.lint_file(os.path.join(FIXTURES, name), REPO)


def _anchors(findings, rule):
    return sorted((f.line, f.suppressed) for f in findings
                  if f.rule == rule)


# ------------------------------------------------------------- purity


def test_purity_fixture_findings_with_anchors():
    fs = _lint("purity_viol.py")
    assert all(not f.suppressed for f in fs)
    host = [f.line for f in fs if f.rule == "purity-host-call"]
    # time.time in a reachable helper; random/os.environ in the root;
    # open/print inside a lax.scan body
    assert host == sorted(host)
    assert set(host) == {18, 25, 26, 38, 40}
    assert [f.line for f in fs if f.rule == "purity-numpy-call"] == [27]
    assert [f.line for f in fs
            if f.rule == "purity-tracer-branch"] == [28, 30, 32]
    # host-side code after the roots is untouched
    assert not any(f.line > 45 for f in fs)
    # file:line anchors are repo-relative and clickable
    assert all(f.path == "tests/data/lint_fixtures/purity_viol.py"
               for f in fs)


def test_obs_in_trace_fixture_findings_with_anchors():
    """The telemetry-purity rule: obs.span()/registry calls inside
    traced code flag (module-attribute, bare-import, and nested-scan
    forms), carry the usual suppression escape, and never fire on the
    host-side instrumentation pattern."""
    fs = _lint("obs_viol.py")
    assert _anchors(fs, "purity-obs-in-trace") == [
        (15, False), (21, False), (23, False), (25, False),
        (31, False), (40, True)]
    # the host-side span/counter block at the bottom stays clean
    assert not any(f.line > 45 and f.rule == "purity-obs-in-trace"
                   for f in fs)


def test_obs_in_trace_repo_sweep_green():
    """The instrumented engine files carry obs calls on the HOST side
    only — the new rule must not fire on the production tree (that is
    the PR's own acceptance: instrumentation never leaked into a
    trace)."""
    for rel in ("jepsen_tpu/parallel/engine.py",
                "jepsen_tpu/parallel/bitdense.py",
                "jepsen_tpu/parallel/sharded.py",
                "jepsen_tpu/parallel/pipeline.py"):
        fs = analysis.lint_file(os.path.join(REPO, rel), REPO)
        bad = [f for f in fs if f.rule == "purity-obs-in-trace"
               and not f.suppressed]
        assert bad == [], "\n".join(f.format() for f in bad)


# ---------------------------------------------------------- recompile


def test_recompile_fixture_findings_with_anchors():
    fs = _lint("recompile_viol.py")
    assert _anchors(fs, "recompile-closure-capture") == [(14, False),
                                                         (22, False)]
    assert _anchors(fs, "recompile-nonliteral-static-args") == \
        [(25, False)]


def test_donate_rule_satisfied_by_explicit_decisions():
    """The donate rule is scoped to the frontier-buffer engines. The
    in-tree jits now all DECIDE donation explicitly — donate_argnames
    on the resumable frontier carries, donate_argnums=() recording
    the nothing-donatable cases — so the rule documents decisions
    instead of being suppressed: zero findings, zero suppressions."""
    for rel in ("jepsen_tpu/parallel/bitdense.py",
                "jepsen_tpu/parallel/engine.py",
                "jepsen_tpu/parallel/dense.py",
                "jepsen_tpu/parallel/sharded.py"):
        fs = analysis.lint_file(os.path.join(REPO, rel), REPO)
        donate = [f for f in fs if f.rule == "recompile-donate-argnums"]
        assert donate == [], (rel, donate)


def test_donate_rule_still_fires_on_undecided_jit(tmp_path):
    """The rule itself stays live: a frontier-engine jit with NO
    donate kwarg (the undecided state this PR eliminated in-tree)
    must still flag."""
    d = tmp_path / "jepsen_tpu" / "parallel"
    d.mkdir(parents=True)
    f = d / "engine.py"
    f.write_text(
        "import jax\n\n\n"
        "def _impl(xs):\n    return xs\n\n\n"
        "_check = jax.jit(_impl, static_argnames=())\n")
    fs = analysis.lint_file(str(f), str(tmp_path))
    donate = [x for x in fs if x.rule == "recompile-donate-argnums"]
    assert donate and not donate[0].suppressed, fs


# -------------------------------------------------------- concurrency


def test_concurrency_fixture_findings_with_anchors():
    fs = _lint("concurrency_viol.py")
    races = _anchors(fs, "concurrency-unlocked-shared-write")
    # unlocked closure write, unlocked global, and an unlocked global
    # write in a BOUND-METHOD thread target (the membership-nemesis
    # shape); the locked variant and main-thread writes stay clean
    assert races == [(17, False), (41, False), (71, False)]


def test_unsupervised_dispatch_fixture_findings_with_anchors():
    """Device-dispatch entry calls outside a supervisor.dispatch thunk
    flag; thunks (lambda, named, via a reachable helper) and the
    rule-named suppression stay clean."""
    fs = _lint("dispatch_viol.py")
    hits = _anchors(fs, "concurrency-unsupervised-dispatch")
    assert hits == [(20, False), (26, False), (52, True)]


def test_env_hygiene_catches_reintroduced_pallas_read():
    """The acceptance regression: a raw JEPSEN_TPU_PALLAS read (what
    bitdense did before the accessor) must be caught with a correct
    anchor; foreign-namespace env reads stay clean."""
    fs = _lint("concurrency_viol.py")
    env = [f for f in fs if f.rule == "env-flag-accessor"]
    assert [(f.line, f.suppressed) for f in env] == \
        [(49, False), (50, False), (51, False)]
    assert "JEPSEN_TPU_PALLAS" in env[0].message
    assert "envflags" in env[0].message
    assert not any("NOT_OURS" in f.message for f in fs)


def test_env_hygiene_allows_the_accessor_module():
    fs = analysis.lint_file(
        os.path.join(REPO, "jepsen_tpu", "envflags.py"), REPO)
    assert not [f for f in fs if f.rule == "env-flag-accessor"]


# ------------------------------------------------------- suppressions


def test_suppressions_honored_and_reported():
    fs = _lint("suppressed_ok.py")
    sup = [f for f in fs if f.suppressed]
    act = [f for f in fs if not f.suppressed]
    # line-level, statement-level (comment above), def-line, and
    # file-level suppressions all honored — and all still REPORTED
    assert {(f.rule, f.line) for f in sup} == {
        ("purity-numpy-call", 15),
        ("purity-host-call", 17),
        ("purity-tracer-branch", 18),
        ("purity-numpy-call", 26),
        ("purity-numpy-call", 27),
        # own-line comment above a DECORATED def (lands on the
        # decorator line) still covers the body
        ("purity-host-call", 48),
        # blank/comment lines between directive and statement don't
        # void the suppression
        ("purity-numpy-call", 69),
    }
    # a bare disable and an unknown rule are findings themselves, and
    # the violations they failed to name stay active; line 59 is the
    # decorated `# jepsen-lint: device` pragma registering its root
    assert _anchors(act, "bad-suppression") == [(33, False), (39, False)]
    assert _anchors(act, "purity-host-call") == [(33, False), (39, False),
                                                 (59, False)]


def test_every_rule_name_documented():
    for rule in lint_core.RULES:
        assert lint_core.RULES[rule], rule


# ------------------------------------------------- repo gate + CLI


def test_repo_lint_is_clean():
    """Zero unsuppressed findings over the production tree, and every
    suppression carries a rule name (bad-suppression is itself a
    finding, so one assert covers both)."""
    findings = analysis.run_lint(root=REPO)
    active = [f for f in findings if not f.suppressed]
    assert active == [], "\n".join(f.format() for f in active)
    # the sweep left a real suppression inventory (donate decisions,
    # trace-constant numpy) — if this drops to zero the rules broke
    assert len(findings) > 10


def test_check_gate_subprocess_no_jax():
    """The tier-1 entry: `python -m jepsen_tpu.analysis --check` exits
    0 on this repo WITHOUT importing jax (pure AST; must stay safe
    under a wedged device runtime)."""
    probe = ("import sys, runpy; sys.argv=['jepsen_tpu.analysis',"
             "'--check']\n"
             "try:\n"
             "    runpy.run_module('jepsen_tpu.analysis',"
             " run_name='__main__')\n"
             "except SystemExit as e:\n"
             "    assert e.code == 0, e.code\n"
             "assert 'jax' not in sys.modules, 'lint imported jax'\n"
             "print('LINT-GATE-OK')\n")
    proc = subprocess.run([sys.executable, "-c", probe],
                          capture_output=True, text=True, timeout=120,
                          cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "LINT-GATE-OK" in proc.stdout


def test_cli_exit_code_contract_and_json():
    """0 clean / 1 findings, both via the library main and the
    `jepsen lint` subcommand; --json emits the stable report shape."""
    import contextlib
    import io

    dirty = os.path.join(FIXTURES, "purity_viol.py")
    clean = os.path.join(REPO, "jepsen_tpu", "envflags.py")
    with contextlib.redirect_stdout(io.StringIO()):
        assert analysis.main([dirty]) == 1
        assert analysis.main([clean]) == 0
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        assert analysis.main([dirty, "--json"]) == 1
    report = json.loads(buf.getvalue())
    assert report["clean"] is False
    assert report["counts"]["active"] == report["counts"]["total"]
    assert set(report["by_rule"]) == {"purity-host-call",
                                      "purity-numpy-call",
                                      "purity-tracer-branch"}

    from jepsen_tpu import cli
    assert cli.main(["lint", dirty]) == 1
    assert cli.main(["lint", clean]) == 0


def test_usage_errors_exit_2_not_1():
    """A typo'd path or unparseable file is a USAGE error (2) — CI
    must not misread it as 'lint found issues' (1)."""
    import contextlib
    import io

    with contextlib.redirect_stderr(io.StringIO()) as err:
        assert analysis.main(["definitely/not/a/file.py"]) == 2
    assert "lint:" in err.getvalue()

    from jepsen_tpu import cli
    with contextlib.redirect_stderr(io.StringIO()):
        assert cli.main(["lint", "definitely/not/a/file.py"]) == 2


def test_undecodable_target_is_a_usage_error(tmp_path):
    """Non-UTF8 bytes in a target file are a usage error (2), not a
    lint verdict (1)."""
    import contextlib
    import io

    bad = tmp_path / "bad_enc.py"
    bad.write_bytes(b'x = "caf\xe9"\n')
    with contextlib.redirect_stderr(io.StringIO()) as err:
        assert analysis.main([str(bad)]) == 2
    assert "lint:" in err.getvalue()


def test_json_stdout_stays_machine_parseable_with_save_store(tmp_path,
                                                             monkeypatch):
    """--json --save-store: stdout is EXACTLY the JSON document; the
    save notice goes to stderr."""
    import contextlib
    import io

    monkeypatch.chdir(tmp_path)   # Store writes ./store relative cwd
    out, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(out), contextlib.redirect_stderr(err):
        rc = analysis.main([os.path.join(FIXTURES, "purity_viol.py"),
                            "--json", "--save-store"])
    assert rc == 1
    report = json.loads(out.getvalue())   # whole stdout parses
    assert report["clean"] is False
    assert "report saved under" in err.getvalue()


# ------------------------------------------------- envflags accessor


def test_envflags_bool_strict_tristate(monkeypatch):
    from jepsen_tpu import envflags

    monkeypatch.delenv("JEPSEN_TPU_PALLAS", raising=False)
    assert envflags.env_bool("JEPSEN_TPU_PALLAS") is None
    assert envflags.env_bool("JEPSEN_TPU_PALLAS", default=True) is True
    monkeypatch.setenv("JEPSEN_TPU_PALLAS", "1")
    assert envflags.env_bool("JEPSEN_TPU_PALLAS") is True
    monkeypatch.setenv("JEPSEN_TPU_PALLAS", "0")
    assert envflags.env_bool("JEPSEN_TPU_PALLAS") is False
    # anything else raises instead of silently counting as opt-out
    for bad in ("yes", "2", "true", ""):
        monkeypatch.setenv("JEPSEN_TPU_PALLAS", bad)
        try:
            envflags.env_bool("JEPSEN_TPU_PALLAS")
            raise AssertionError(f"{bad!r} did not raise")
        except envflags.EnvFlagError as e:
            assert "JEPSEN_TPU_PALLAS" in str(e)


def test_envflags_choice_and_namespace_guard(monkeypatch):
    from jepsen_tpu import envflags

    monkeypatch.delenv("JEPSEN_TPU_BUCKET", raising=False)
    assert envflags.env_choice("JEPSEN_TPU_BUCKET", ("tier", "exact"),
                               default="tier") == "tier"
    monkeypatch.setenv("JEPSEN_TPU_BUCKET", "exact")
    assert envflags.env_choice("JEPSEN_TPU_BUCKET",
                               ("tier", "exact")) == "exact"
    monkeypatch.setenv("JEPSEN_TPU_BUCKET", "bogus")
    try:
        envflags.env_choice("JEPSEN_TPU_BUCKET", ("tier", "exact"),
                            what="bucket strategy")
        raise AssertionError("bogus did not raise")
    except envflags.EnvFlagError as e:
        assert "bucket strategy" in str(e)
    # EnvFlagError is a ValueError: existing pytest.raises(ValueError)
    # call sites keep working
    assert issubclass(envflags.EnvFlagError, ValueError)
    # the accessor refuses foreign namespaces
    try:
        envflags.env_raw("HOME")
        raise AssertionError("foreign namespace did not raise")
    except envflags.EnvFlagError:
        pass


def test_envflags_int_accessor(monkeypatch):
    from jepsen_tpu import envflags

    monkeypatch.delenv("JEPSEN_TPU_ENCODE_CACHE", raising=False)
    assert envflags.env_int("JEPSEN_TPU_ENCODE_CACHE") is None
    assert envflags.env_int("JEPSEN_TPU_ENCODE_CACHE",
                            default=256) == 256
    monkeypatch.setenv("JEPSEN_TPU_ENCODE_CACHE", "0")
    assert envflags.env_int("JEPSEN_TPU_ENCODE_CACHE",
                            default=256, min_value=0) == 0
    monkeypatch.setenv("JEPSEN_TPU_ENCODE_CACHE", "1024")
    assert envflags.env_int("JEPSEN_TPU_ENCODE_CACHE") == 1024
    # malformed or below-floor values fail loudly, never silently
    # revert to the default (the envflags contract)
    for bad in ("many", "1.5", ""):
        monkeypatch.setenv("JEPSEN_TPU_ENCODE_CACHE", bad)
        try:
            envflags.env_int("JEPSEN_TPU_ENCODE_CACHE")
            raise AssertionError(f"{bad!r} did not raise")
        except envflags.EnvFlagError as e:
            assert "JEPSEN_TPU_ENCODE_CACHE" in str(e)
    monkeypatch.setenv("JEPSEN_TPU_ENCODE_CACHE", "-3")
    try:
        envflags.env_int("JEPSEN_TPU_ENCODE_CACHE", min_value=0)
        raise AssertionError("below-floor did not raise")
    except envflags.EnvFlagError as e:
        assert ">= 0" in str(e)


def test_resolve_use_pallas_rejects_malformed_flag(monkeypatch):
    """The satellite regression: JEPSEN_TPU_PALLAS outside {'0','1'}
    must raise at resolve time, not silently disable the measured
    pallas default."""
    import pytest

    from jepsen_tpu import envflags
    from jepsen_tpu.parallel import bitdense

    monkeypatch.setenv("JEPSEN_TPU_PALLAS", "yes")
    with pytest.raises(envflags.EnvFlagError, match="JEPSEN_TPU_PALLAS"):
        bitdense._resolve_use_pallas(None, 17, 12, "axon")
    # an explicit argument bypasses the env read entirely
    assert bitdense._resolve_use_pallas(False, 17, 12, "axon") \
        == (False, False)


def test_lint_report_saves_into_store_run_dir(tmp_path):
    """JSON + human reports ride the store.py run-dir lifecycle."""
    from jepsen_tpu import store as jstore

    findings = analysis.lint_file(
        os.path.join(FIXTURES, "suppressed_ok.py"), REPO)
    st = jstore.Store("lint-test", base_dir=str(tmp_path))
    d = analysis.save_to_store(findings, st)
    data = json.loads(open(os.path.join(d, "lint.json")).read())
    assert data["counts"]["total"] == len(findings)
    txt = open(os.path.join(d, "lint.txt")).read()
    assert "[suppressed]" in txt and "bad-suppression" in txt


# ------------------------------------------- lock discipline (PR 16)


def test_lock_order_cycle_fixture():
    fs = _lint("locks_viol.py")
    assert _anchors(fs, "concurrency-lock-order") == [(29, False)]
    msg = [f.message for f in fs
           if f.rule == "concurrency-lock-order"][0]
    assert "Cycle._a" in msg and "Cycle._b" in msg


def test_blocking_under_lock_fixture_and_pr8_regression():
    fs = _lint("locks_viol.py")
    assert _anchors(fs, "concurrency-blocking-under-lock") == [
        (49, False), (54, False), (55, False), (56, False),
        (61, False), (68, False)]
    by_line = {f.line: f.message for f in fs
               if f.rule == "concurrency-blocking-under-lock"}
    # the PR-8 regression shape: a flight dump (file I/O) inside the
    # service condition
    assert "flight_dump" in by_line[49]
    assert "Dumper._cond" in by_line[49]
    # the one-level self.method() inlining names the calling context
    assert "inlined from `Dumper.outer`" in by_line[68]
    # wait() on the condition the function HOLDS (line 50) is the
    # sanctioned idiom — wait releases it
    assert 50 not in by_line


def test_unguarded_field_fixture_pr11_regression():
    """The PR-11 shape: a worker-thread write to a field every other
    writer touches under the lock."""
    fs = _lint("locks_viol.py")
    assert _anchors(fs, "concurrency-unguarded-field") == [(96, False)]
    msg = [f.message for f in fs
           if f.rule == "concurrency-unguarded-field"][0]
    assert "9/10" in msg and "read-modify-write" in msg
    assert "Tally._lock" in msg


def test_lock_rules_silent_on_clean_twin():
    """Consistent order, I/O outside locks, wait-on-held-cond, fully
    guarded field, explicit acquire/release: zero findings of ANY
    rule."""
    assert _lint("locks_ok.py") == []


def test_cross_module_pair_cycle():
    from jepsen_tpu.analysis import locks
    sa = lint_core.SourceFile(
        os.path.join(FIXTURES, "pair_svc.py"), REPO)
    sb = lint_core.SourceFile(
        os.path.join(FIXTURES, "pair_wal.py"), REPO)
    fs = locks.pair_findings(sa, sb, r"wal", r"svc")
    assert len(fs) == 1 and fs[0].rule == "concurrency-lock-order"
    assert "closes across" in fs[0].message
    assert "Service._lock" in fs[0].message
    assert "Wal._mu" in fs[0].message
    # each side alone is clean — the cycle exists only in the pair
    # graph, which is exactly why the sweep runs the pair pass
    assert _anchors(_lint("pair_svc.py"),
                    "concurrency-lock-order") == []
    assert _anchors(_lint("pair_wal.py"),
                    "concurrency-lock-order") == []


def test_stale_suppression_fixture():
    fs = _lint("stale_viol.py")
    # the dead directive is a finding anchored at ITS OWN line, and
    # it is not suppressible
    assert _anchors(fs, "lint-stale-suppression") == [(16, False)]
    # the used directive is NOT stale — its finding stays reported,
    # marked suppressed
    assert _anchors(fs, "env-flag-accessor") == [(12, True)]


def test_repo_suppression_inventory_is_live():
    """The audited WAL suppressions are real: the repo sweep carries
    SUPPRESSED blocking-under-lock findings (fsync under the per-key
    handoff lock), and zero stale directives anywhere."""
    findings = analysis.run_lint(root=REPO)
    assert any(f.rule == "concurrency-blocking-under-lock"
               and f.suppressed and f.path.endswith("serve/wal.py")
               for f in findings)
    assert not any(f.rule == "lint-stale-suppression"
                   for f in findings)


# ------------------------------------------------------- drift gates


def test_flag_drift_fixture():
    from jepsen_tpu.analysis import drift
    root = os.path.join(FIXTURES, "driftrepo")
    fs = drift.flag_findings(root, "envflags.py", ("docs/flags.md",))
    assert sorted((f.path, f.line) for f in fs) == [
        ("docs/flags.md", 6), ("envflags.py", 8)]
    msgs = " ".join(f.message for f in fs)
    assert "JEPSEN_TPU_BETA" in msgs and "JEPSEN_TPU_GAMMA" in msgs
    # the clean flag never shows up
    assert "JEPSEN_TPU_ALPHA" not in msgs


def test_metric_drift_fixture():
    from jepsen_tpu.analysis import drift
    root = os.path.join(FIXTURES, "driftrepo")
    fs = drift.metric_findings(root,
                               [os.path.join(root, "mints.py")],
                               "docs/obs.md")
    assert sorted((f.path, f.line) for f in fs) == [
        ("docs/obs.md", 12), ("mints.py", 13)]
    msgs = " ".join(f.message for f in fs)
    assert "app.orphan" in msgs and "app.ghost" in msgs
    # shorthand/label/wildcard rows all matched their mints: the
    # leading-dot pair, the [tenant=<t>] base, the f-string pattern
    for clean in ("app.hits", "app.misses", "app.depth",
                  "app.latency", "app.dyn"):
        assert clean not in msgs


def test_drift_gates_pass_against_live_docs():
    """The acceptance pin: drift found during the PR was FIXED in the
    docs, not suppressed — both gates are empty on the live tree."""
    from jepsen_tpu.analysis import drift
    assert drift.flag_findings(REPO) == []
    assert drift.metric_findings(
        REPO, lint_core.default_targets(REPO)) == []


def test_drift_gates_skipped_for_explicit_paths():
    """Linting one file never drags in the repo-wide doc checks."""
    fs = analysis.run_lint([os.path.join(FIXTURES, "locks_ok.py")],
                           root=REPO)
    assert fs == []


# ----------------------------------------------------- --changed mode


def test_changed_mode_contract():
    import contextlib
    import io
    buf, err = io.StringIO(), io.StringIO()
    with contextlib.redirect_stdout(buf), \
            contextlib.redirect_stderr(err):
        # a bad base ref and mixing --changed with explicit paths are
        # USAGE errors (2), never lint verdicts
        assert analysis.main(["--changed", "no-such-ref-xyz"]) == 2
        assert analysis.main(["jepsen_tpu", "--changed"]) == 2
        # the fast path itself: a clean tree (or clean changed files)
        # exits 0, same contract as the full gate
        assert analysis.main(["--changed"]) == 0


def test_changed_files_shape():
    files = analysis.changed_files(root=REPO)
    assert isinstance(files, list)
    for p in files:
        assert p.endswith(".py") and os.path.isfile(p)
        rel = os.path.relpath(p, REPO)
        top = rel.split(os.sep, 1)[0]
        assert top in ("jepsen_tpu", "tools") \
            or rel in ("bench.py", "__graft_entry__.py")
