"""The driver's scoreboard (bench.py) must never be broken in CI.

The real bench runs on TPU at round end; these tests exercise the
ORCHESTRATION on CPU so a bench.py regression (import error, JSON
contract break, hang-isolation bug) surfaces in the suite instead of
at scoring time:

1. forced-hang drive: with TIMEOUT_SCALE tiny every section is killed;
   the parent must still emit machine-readable skip lines and a final
   headline line, and exit 0;
2. one real section (adversarial smoke size) end-to-end, checking the
   driver-parsed JSON contract {"metric", "value", "unit",
   "vs_baseline"}.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, args=(), timeout=240):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}   # never touch a TPU tunnel
    env.update({"BENCH_SMOKE": "1", "JAX_PLATFORMS": "cpu"})
    env.update(env_extra)
    return subprocess.run([sys.executable, BENCH, *args],
                          capture_output=True, text=True, env=env,
                          timeout=timeout)


def _json_lines(out):
    return [json.loads(ln) for ln in out.splitlines()
            if ln.lstrip().startswith("{")]


@pytest.mark.slow
def test_bench_total_hang_lands_on_labeled_cpu_fallback():
    """Every device section killed -> the bench runs one CPU-fallback
    multikey and the headline (and the child's own forwarded line) are
    BOTH labeled — no unlabeled line may claim a device number.

    The parent runs the PRODUCTION (non-smoke) configuration: the
    fallback child must be forced onto SMOKE shapes regardless, because
    the full 84-key batch cannot finish on a host CPU inside any window
    (BENCH_r03's fallback recorded null for exactly this reason).

    BENCH_PROBE_TIMEOUT is pinned high so the cpu-pinned pre-probe
    SUCCEEDS and this test keeps covering the per-section
    hang-isolation + retry machinery (the probe-skip path has its own
    test below)."""
    r = _run({"BENCH_TIMEOUT_SCALE": "0.02", "BENCH_SMOKE": "",
              "BENCH_PROBE_TIMEOUT": "6000"},
             timeout=500)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = _json_lines(r.stdout)
    assert any(l.get("metric") == "device pre-probe" for l in lines), \
        "probe was meant to pass in this test"
    skips = [l for l in lines if "timeout/hang" in str(l.get("skipped"))]
    assert skips, "no per-section hang-kill skip lines emitted"
    head = lines[-1]
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in head, head
    assert head.get("backend") == "cpu-fallback", head
    assert "CPU FALLBACK" in head["metric"]
    assert "8x40" in head["metric"], head          # smoke shapes forced
    assert "84x120" not in head["metric"], head    # not the full batch
    for l in lines:
        if l.get("value") is not None and "metric" in l:
            assert "device end-to-end" not in l["metric"], l


@pytest.mark.slow
def test_bench_hang_plus_exhausted_budget_emits_error_headline():
    """When even the fallback can't run (budget already negative, so
    its timeout collapses and it is killed too), the final line is the
    machine-readable error headline."""
    r = _run({"BENCH_TIMEOUT_SCALE": "0.02", "BENCH_BUDGET_SECS": "4"})
    assert r.returncode == 0, r.stderr[-2000:]
    lines = _json_lines(r.stdout)
    head = lines[-1]
    assert head["value"] is None and "error" in head, head
    for k in ("metric", "value", "unit", "vs_baseline"):
        assert k in head, head


@pytest.mark.slow
def test_bench_wedged_runtime_fails_once_and_finishes_fast():
    """A dead device runtime must be discovered ONCE by the bounded
    pre-probe, not once per device section (BENCH_r04 burned ~13 min
    of budget rediscovering the same wedge four times, one 180s+
    timeout each). With every non-cpu child wedged via the test seam
    (JEPSEN_TPU_TEST_WEDGE simulates the PJRT hang; cpu-pinned
    children survive, as in production), the FULL production-shape
    bench must land the labeled CPU-fallback headline in under 60s."""
    t0 = time.monotonic()
    r = _run({"BENCH_SMOKE": "", "JAX_PLATFORMS": "",
              "JEPSEN_TPU_TEST_WEDGE": "1", "BENCH_PROBE_TIMEOUT": "5"},
             timeout=120)
    wall = time.monotonic() - t0
    assert r.returncode == 0, r.stderr[-2000:]
    assert wall < 60, f"wedged bench took {wall:.0f}s (budget: <60s)"
    lines = _json_lines(r.stdout)
    head = lines[-1]
    assert head.get("backend") == "cpu-fallback", head
    assert "CPU FALLBACK" in head["metric"], head
    assert "8x40" in head["metric"], head          # smoke shapes forced
    # a fallback must still point at the committed hardware evidence
    # (bench_results/*_onchip.jsonl exists in-repo since r5)
    prior = head.get("prior_onchip_headline")
    assert prior and prior["backend"] == "tpu", head
    assert "NOT this run's measurement" in prior["note"], prior
    # every device section got its own machine-readable skip line,
    # all attributed to the single pre-probe failure
    skips = [l for l in lines
             if "pre-probe" in str(l.get("skipped", ""))]
    assert len(skips) >= 7, lines   # multikey + 4 adv + sharded + maxlen


@pytest.mark.slow
def test_bench_big_shapes_preflight_on_cpu():
    """No bench shape may be first-exercised on expensive hardware:
    the 10k/50k adversarial history build + encode + the packed-host
    duel — exactly what bench.sec_adv runs before its device call —
    must complete green on CPU inside the bench's own deadlines.
    (The device call itself is covered at these shapes by maxlen's CPU
    smoke at 51200 ops and the adv section contract test.)"""
    import importlib
    from time import monotonic, perf_counter

    import bench
    from jepsen_tpu.checker import linear_packed
    from jepsen_tpu.parallel import bitdense

    if bench.SMOKE or bench.ADV_K != 12:
        # module-level shape constants read the env at import: pin the
        # production shapes regardless of ambient BENCH_* vars (the
        # sibling tests get this for free by running bench via _run())
        for var in ("BENCH_SMOKE", "BENCH_ADV_K"):
            os.environ.pop(var, None)
        bench = importlib.reload(bench)
    assert bench.ADV_K == 12, "preflight must cover the bench's real k"
    for L in (10000, 50000):
        t0 = perf_counter()
        _, _, e, _ = bench._adv_encoded(L)
        build_secs = perf_counter() - t0
        assert build_secs < 60, (L, build_secs)
        assert bitdense.fits_bitdense(bitdense.n_states(e), e.n_slots)
        deadline = bench.HOST_DEADLINES[L]
        t0 = perf_counter()
        rh = linear_packed.check_encoded(e,
                                         deadline=monotonic() + deadline)
        wall = perf_counter() - t0
        # the duel must respect its deadline (+grace for one event) and
        # either finish or report real progress the estimate scales from
        assert wall < deadline + 10, (L, wall)
        if rh["valid?"] == "unknown":
            assert rh.get("events-done", 0) > 0, rh
        else:
            assert rh["valid?"] is True, rh


@pytest.mark.slow
def test_bench_adv_section_contract():
    r = _run({}, args=["--section", "adv", "200", "5", "0", "",
                       "--timeout", "200"])
    assert r.returncode == 0, r.stderr[-2000:]
    lines = _json_lines(r.stdout)
    # the main device line, then the sparse-engine dedupe A/B advisory
    assert len(lines) == 2, lines
    line = lines[0]
    for k in ("metric", "value", "unit", "vs_baseline", "L",
              "device_secs", "host_est_secs",
              # the per-section encode/transfer/device split keys —
              # every device section must carry them so pipeline wins
              # are measurable against prior artifacts
              "encode_secs", "transfer_secs",
              # the uniform dedupe schema: strategy + configs-stepped
              # counter in every device section (bitdense sections
              # report "dense"/None; real counters ride the advisory)
              "dedupe", "configs_stepped"):
        assert k in line, line
    assert line["L"] == 200 and line["value"] > 0
    assert line["unit"] == "ops/sec"
    assert line["dedupe"] == "dense", line
    assert line["encode_secs"] >= 0 and line["transfer_secs"] >= 0
    # device_secs is uniformly SEARCH-ONLY across sections; the old
    # whole-call quantity lives on as steady_secs in this section
    assert line["device_secs"] <= line["steady_secs"], line
    # the dedupe A/B advisory: both strategies decided the key, and the
    # delta-frontier counter is STRICTLY below the sort path's on this
    # adversarial shape — the work reduction, visible on CPU
    ab = lines[1]
    assert "dedupe A/B" in ab["metric"], ab
    for strat in ("sort", "hash"):
        d = ab["dedupe"][strat]
        assert d["valid"] is True and d["configs_stepped"] > 0, ab
    assert ab["dedupe"]["hash"]["configs_stepped"] \
        < ab["dedupe"]["sort"]["configs_stepped"], ab


@pytest.mark.slow
def test_bench_multikey_section_contract():
    """The multikey section must emit BOTH the serial device line
    (with the encode/transfer/device split keys) and the pipelined
    line (with the per-bucket split + cache counters showing the
    second pass re-encoded nothing)."""
    r = _run({}, args=["--section", "multikey", "--timeout", "200"],
             timeout=240)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = _json_lines(r.stdout)
    serial = [l for l in lines if "north-star shape" in l["metric"]
              and "pipelined" not in l["metric"]]
    piped = [l for l in lines if "pipelined" in l["metric"]]
    assert len(serial) == 1 and len(piped) == 1, lines
    for k in ("encode_secs", "transfer_secs", "device_secs",
              "device_only_secs", "dedupe", "configs_stepped"):
        assert k in serial[0], serial[0]
    # bitdense batch: the dense tensor is itself the visited set
    assert serial[0]["dedupe"] == "dense", serial[0]
    p = piped[0]
    assert p["dedupe"] in ("sort", "hash"), p   # the resolved strategy
    for k in ("serial_e2e_secs", "pipelined_e2e_secs",
              "cached_e2e_secs", "buckets", "cache"):
        assert k in p, p
    assert p["cache"]["encodes"] == 0, p["cache"]
    for b in p["buckets"]:
        for k in ("tier", "keys", "engine", "encode_secs",
                  "transfer_secs", "device_wait_secs"):
            assert k in b, b


def test_sharded_section_line_carries_dedupe_schema(monkeypatch,
                                                    capsys):
    """The sharded section's JSON line must carry the uniform dedupe
    schema — the ACTIVE strategy and the real configs-stepped counter
    from the engine result (this is the section where the counter is a
    genuine int, not the bitdense "dense"/None placeholder). The
    engine is stubbed: its own result keys are pinned by
    tests/test_sharded.py; this pins the result->line mapping without
    paying a multi-minute sharded search in CI."""
    import importlib
    import bench
    from jepsen_tpu.parallel import sharded

    canned = {"valid?": True, "devices": 8, "capacity": 4096,
              "max-frontier": 7, "dedupe": "hash",
              "configs-stepped": 12345}
    monkeypatch.setattr(sharded, "check_encoded_sharded",
                        lambda *a, **k: dict(canned))
    monkeypatch.setattr(bench, "ADV_K", 4)   # tiny encode, same path
    bench.sec_sharded(64, None, cap_log=8)
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1, lines
    line = lines[0]
    for k in ("metric", "value", "unit", "vs_baseline", "dedupe",
              "configs_stepped", "device_secs", "encode_secs",
              "transfer_secs"):
        assert k in line, line
    assert line["dedupe"] == "hash"
    assert line["configs_stepped"] == 12345
    # the telemetry schema pin: with tracing OFF (the default here) the
    # line carries NO trace pointer — the split-line contract is
    # byte-for-byte the historical one
    assert "trace" not in line, line
    importlib.reload(bench)


def test_bench_stream_section_contract(monkeypatch, capsys):
    """The BENCH_STREAM-gated streaming advisory: its line schema when
    it runs, and the default schema's byte-identity when it doesn't —
    main() only spawns the section under BENCH_STREAM=1, so with the
    flag unset no new line ever appears (the sparse-pallas-advisory
    gating precedent)."""
    import bench

    monkeypatch.setenv("BENCH_STREAM_OPS", "60")
    monkeypatch.setenv("BENCH_STREAM_DELTAS", "3")
    bench.sec_stream()
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1, lines
    line = lines[0]
    for k in ("metric", "value", "unit", "vs_baseline", "stream"):
        assert k in line, line
    assert "[advisory]" in line["metric"]
    st = line["stream"]
    for k in ("deltas", "ops", "incremental_secs", "full_secs",
              "speedup", "verdicts_match", "final_resume_event"):
        assert k in st, st
    # the acceptance property rides the bench too: delta-fed and
    # one-shot verdicts agree on every prefix the section compared
    assert st["verdicts_match"] is True
    assert st["final_resume_event"] > 0
    # gating pin: the parent only runs the section behind the flag
    with open(bench.__file__) as fh:
        src = fh.read()
    assert 'os.environ.get("BENCH_STREAM") == "1"' in src


def test_bench_search_stats_line_gated_on_flag(monkeypatch, capsys):
    """The stats-gated occupancy/pad-waste advisory (ISSUE 10): with
    JEPSEN_TPU_SEARCH_STATS unset, emit_search_stats is a no-op — the
    default bench schema stays byte-identical (the sharded-section
    single-line pin above covers the section path); with the flag on,
    one advisory line summarizing the results' device-computed stats
    blocks."""
    import bench

    results = [{"valid?": True,
                "stats": {"engine": "sparse", "events": 10,
                          "frontier-peak": 40,
                          "peak-occupancy": 0.3125,
                          "load-factor-peak": 0.15625,
                          "capacity-tier": 1,
                          "pad-waste": 0.25,
                          "probe-hist": {"0": 90, "1": 10}}},
               {"valid?": True, "stats": {"engine": "sparse",
                                          "events": 4,
                                          "frontier-peak": 8,
                                          "peak-occupancy": 0.0625,
                                          "capacity-tier": 0}}]
    monkeypatch.delenv("JEPSEN_TPU_SEARCH_STATS", raising=False)
    bench.emit_search_stats("testsec", results)
    assert _json_lines(capsys.readouterr().out) == []
    # results without stats blocks (flag raced off mid-run) stay quiet
    monkeypatch.setenv("JEPSEN_TPU_SEARCH_STATS", "1")
    bench.emit_search_stats("testsec", [{"valid?": True}])
    assert _json_lines(capsys.readouterr().out) == []
    bench.emit_search_stats("testsec", results, {"L": 64})
    lines = _json_lines(capsys.readouterr().out)
    assert len(lines) == 1, lines
    line = lines[0]
    assert line["unit"] == "peak-occupancy"
    assert line["value"] == 0.3125          # max over keys
    assert line["keys"] == 2 and line["L"] == 64
    assert line["frontier_peak"] == 40
    assert line["load_factor_peak"] == 0.15625
    assert line["pad_waste_max"] == 0.25
    assert line["probe_hist"] == {"0": 90, "1": 10}
    assert line["escalated_keys"] == 1
    assert "JEPSEN_TPU_SEARCH_STATS" in line["metric"]


def test_bench_emit_trace_pointer_gated_on_tracing(monkeypatch,
                                                   capsys):
    """Sections stamp `trace=<relpath>` onto their JSON lines exactly
    when tracing is on (TRACE_REL set by child_main): the pointer
    appears on every line of a traced section and on none of an
    untraced one, and never clobbers an explicit key."""
    import bench

    bench_line = {"metric": "m", "value": 1.0, "unit": "ops/sec",
                  "vs_baseline": None}
    monkeypatch.setattr(bench, "TRACE_REL", None)
    bench.emit(dict(bench_line))
    off = _json_lines(capsys.readouterr().out)[0]
    assert "trace" not in off
    rel = "store/bench_traces/bench_adv.trace.json"
    monkeypatch.setattr(bench, "TRACE_REL", rel)
    bench.emit(dict(bench_line))
    on = _json_lines(capsys.readouterr().out)[0]
    assert on["trace"] == rel
    # identical schema otherwise
    assert {k: v for k, v in on.items() if k != "trace"} == off


def test_bench_child_trace_suffix_and_crash_write(tmp_path):
    """A retry child's chrome trace lands at a `_<suffix>`-suffixed
    filename (so a retry can never overwrite the file the first
    attempt's emitted lines point at), and the trace is written even
    when the section body raises — the finally-block export."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"JAX_PLATFORMS": "cpu", "JEPSEN_TPU_TRACE": "1",
                "PYTHONPATH": REPO})
    r = subprocess.run(
        [sys.executable, BENCH, "--section", "nosuch",
         "--timeout", "60", "--trace-suffix", "retry"],
        capture_output=True, text=True, env=env, cwd=tmp_path,
        timeout=120)
    assert r.returncode != 0           # unknown section exits nonzero
    trace = tmp_path / "store" / "bench_traces" / \
        "bench_nosuch_retry.trace.json"
    assert trace.is_file(), (r.stdout, r.stderr)
    assert isinstance(json.loads(trace.read_text()), list)


def test_run_section_threads_trace_suffix(monkeypatch, capsys):
    """run_section forwards trace_suffix to the child argv as
    `--trace-suffix <s>` (and omits the flag entirely when empty) —
    the parent-side half of the retry-filename contract."""
    import bench

    cmds = []

    def fake_popen(cmd, **kw):
        cmds.append(cmd)
        raise OSError("not really spawning")

    monkeypatch.setattr(bench.subprocess, "Popen", fake_popen)
    bench.run_section(["multikey"], 60, trace_suffix="retry")
    bench.run_section(["multikey"], 60)
    capsys.readouterr()
    i = cmds[0].index("--trace-suffix")
    assert cmds[0][i + 1] == "retry"
    assert "--trace-suffix" not in cmds[1]


def test_prior_onchip_headline_orders_by_round_number(tmp_path,
                                                      monkeypatch):
    """Artifact selection must rank bench_r<N>_onchip.jsonl by PARSED
    round number — git checkouts do not preserve mtime, so a fresh
    clone can easily give an older round the newest mtime. Unparsable
    names fall back to mtime and rank below any parsed round."""
    import importlib

    import bench

    results = tmp_path / "bench_results"
    results.mkdir()

    def write(name, value, backend="tpu"):
        p = results / name
        p.write_text(json.dumps({"metric": "headline", "value": value,
                                 "vs_baseline": 1.0,
                                 "backend": backend}) + "\n")
        return p

    r2 = write("bench_r2_onchip.jsonl", 222.0)
    r10 = write("bench_r10_onchip.jsonl", 1010.0)
    # checkout order inverted: the OLD round has the NEWEST mtime (and
    # a filename sort would also pick r2 over r10)
    now = time.time()
    os.utime(r10, (now - 1000, now - 1000))
    os.utime(r2, (now, now))

    monkeypatch.setattr(bench, "__file__",
                        str(tmp_path / "bench.py"))
    prior = bench._prior_onchip_headline()
    assert prior is not None and prior["value"] == 1010.0, prior
    assert prior["file"].endswith("bench_r10_onchip.jsonl"), prior

    # a no-round artifact with the newest mtime still loses to a
    # parsed round...
    noround = write("bench_manual_onchip.jsonl", 555.0)
    os.utime(noround, (now + 10, now + 10))
    assert bench._prior_onchip_headline()["value"] == 1010.0

    # ...but decides by mtime when no round parses anywhere
    r2.unlink()
    r10.unlink()
    write("bench_alpha_onchip.jsonl", 111.0)
    os.utime(results / "bench_alpha_onchip.jsonl", (now - 50, now - 50))
    assert bench._prior_onchip_headline()["value"] == 555.0
    importlib.reload(bench)


def test_bench_elastic_advisory_lines_gated_on_flags(monkeypatch, capsys):
    """The ISSUE 15 elastic advisories: with JEPSEN_TPU_STEAL /
    JEPSEN_TPU_RESHARD unset, emit_steal_advisory and
    emit_reshard_advisory are no-ops BEFORE touching any argument or
    backend — the default bench schema stays byte-identical (the
    emit_search_stats gating precedent above)."""
    import bench

    monkeypatch.delenv("JEPSEN_TPU_STEAL", raising=False)
    bench.emit_steal_advisory("testsec")
    assert _json_lines(capsys.readouterr().out) == []
    monkeypatch.delenv("JEPSEN_TPU_RESHARD", raising=False)
    # args deliberately unusable: the gate must return first
    bench.emit_reshard_advisory(None, None, 0, 0, {}, 0.0)
    assert _json_lines(capsys.readouterr().out) == []
    # a malformed flag value raises (the envflags contract), never a
    # silent skip
    monkeypatch.setenv("JEPSEN_TPU_STEAL", "maybe")
    import pytest as _pytest
    from jepsen_tpu.envflags import EnvFlagError
    with _pytest.raises(EnvFlagError):
        bench.emit_steal_advisory("testsec")
