"""Randomized differential fuzz: every engine vs the host WGL oracle.

All six model families, clean + corrupted histories, every engine whose
gate admits the shape (host linear / packed, device sparse / dense /
bitdense). The reference runs its expensive tiers outside the default
selection (`lein test` excludes :perf/:integration —
jepsen/project.clj:36-41); likewise this tier is deselected by default
(pytest.ini addopts) and run explicitly:

    python -m pytest tests/test_fuzz_differential.py -m fuzz -q

Seed count via JEPSEN_FUZZ_SEEDS (default 3 per model-variant; for a
deep sweep run e.g. `JEPSEN_FUZZ_SEEDS=30 ... -m fuzz`). Any verdict
disagreement or engine crash fails the test with the (model, seed,
variant) triple — enough to reproduce deterministically.
"""

import os
import traceback
from time import monotonic

import numpy as np
import pytest

from jepsen_tpu.checker import competition, linear, linear_packed, wgl
from jepsen_tpu.histories import (
    corrupt_history, rand_fifo_history, rand_gset_history,
    rand_queue_history, rand_register_history)
from jepsen_tpu.history import History
from jepsen_tpu.models import (
    CASRegister, FIFOQueue, GSet, Mutex, Register, UnorderedQueue)
from jepsen_tpu.parallel import bitdense, dense, encode as enc_mod, engine

N_SEEDS = int(os.environ.get("JEPSEN_FUZZ_SEEDS", "3"))


def rand_mutex_history(n_ops, n_processes, crash_p, seed):
    """Random acquire/release attempts; validity NOT guaranteed —
    the differential compares verdicts, it does not assert them.
    Crashed (info) workers retire their process id for a fresh one,
    matching the interpreter's renumbering convention (History.pairs
    assumes one open op per process id)."""
    rng = np.random.default_rng(seed)
    ops, t = [], 0
    pid_of = dict(enumerate(range(n_processes)))   # worker -> live pid
    next_pid = n_processes
    open_w = {}                                    # worker -> open f
    for _ in range(n_ops):
        w = int(rng.integers(n_processes))
        if w in open_w:
            f = open_w.pop(w)
            typ = "info" if rng.random() < crash_p else "ok"
            ops.append({"index": len(ops), "time": t,
                        "process": pid_of[w], "type": typ, "f": f,
                        "value": None})
            if typ == "info":
                pid_of[w] = next_pid
                next_pid += 1
        else:
            f = "acquire" if rng.random() < 0.5 else "release"
            open_w[w] = f
            ops.append({"index": len(ops), "time": t,
                        "process": pid_of[w], "type": "invoke", "f": f,
                        "value": None})
        t += 1
    for w, f in open_w.items():
        ops.append({"index": len(ops), "time": t, "process": pid_of[w],
                    "type": "info", "f": f, "value": None})
        t += 1
    return History.wrap(ops).index()


CASES = [
    ("cas-register", CASRegister,
     lambda s: rand_register_history(n_ops=44, n_processes=5, n_values=3,
                                     crash_p=0.06, fail_p=0.08, seed=s)),
    ("register", Register,
     lambda s: rand_register_history(n_ops=40, n_processes=4, n_values=3,
                                     crash_p=0.05, fail_p=0.05, seed=s,
                                     cas=False)),
    ("mutex", Mutex,
     lambda s: rand_mutex_history(36, 4, 0.05, s)),
    ("gset", GSet,
     lambda s: rand_gset_history(n_ops=40, n_processes=4, n_elements=6,
                                 crash_p=0.06, seed=s)),
    # queue families stay small: proving a corrupted queue history
    # invalid forces the host searches to exhaust the interleaving
    # space, which grows brutally with length (the device engines
    # don't care — but the oracle must terminate)
    ("uqueue", UnorderedQueue,
     lambda s: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                  crash_p=0.06, seed=s)),
    ("fifo", FIFOQueue,
     lambda s: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                                 crash_p=0.05, seed=s)),
]


@pytest.mark.fuzz
@pytest.mark.parametrize("name,Model,gen", CASES,
                         ids=[c[0] for c in CASES])
def test_fuzz_engines_agree_with_wgl(name, Model, gen):
    import jax

    failures = []
    runs = 0
    for seed in range(N_SEEDS):
        if seed and seed % 25 == 0:
            # every distinct (R, S, C) shape is a separate compiled
            # executable; hundreds of seeds accumulate thousands of
            # them and the XLA CPU backend has been observed to
            # SEGFAULT under that pressure (200-seed sweep crash in
            # backend_compile_and_load; all shapes pass in isolation)
            jax.clear_caches()
        # mutex ops carry no values, so corrupt_history has nothing to
        # flip — its invalid coverage comes from the clean variant,
        # where random acquire/release interleavings are often already
        # invalid (the oracle decides); every other family gets a
        # value-corrupted variant (reads and dequeues)
        variants = ("clean",) if name == "mutex" else ("clean", "corrupt")
        for variant in variants:
            h = gen(seed)
            if variant == "corrupt":
                h = corrupt_history(h, seed=seed, n_corruptions=2)
            model = Model()
            # pure-Python search: some seeds are pathologically
            # expensive (exponential in open calls) — bound the oracle
            # and skip undecided cases rather than hang the tier
            oracle = wgl.analysis(model, h, max_states=1_000_000,
                                  deadline=monotonic() + 8)["valid?"]
            if oracle == "unknown":
                continue
            engines = {"linear": lambda: linear.analysis(
                model, h, deadline=monotonic() + 10),
                # the full first-decisive-wins race (jax+packed+wgl or
                # linear+wgl): whatever arm wins must agree with the
                # oracle — this is the DEFAULT analyzer users get
                "competition": lambda: competition.analysis(
                    model, h, timeout=30)}
            try:
                e = enc_mod.encode(model, h)
            except enc_mod.EncodeError:
                e = None
            if e is not None:
                engines["packed"] = lambda: linear_packed.analysis(
                    model, h, deadline=monotonic() + 10)
                # check_encoded directly: engine.analysis would route
                # to bitdense for most of these shapes, silently
                # re-testing what the separate bitdense entry covers.
                # Invalid queue histories never prune, so the sparse
                # frontier escalates tier-by-tier (minutes on the CPU
                # mesh); cap it — overflow returns "unknown", skipped
                # by the loop below
                engines["sparse"] = lambda: engine.check_encoded(
                    e, max_capacity=1 << 15)
                # the delta-frontier hash visited-set variant
                # (JEPSEN_TPU_DEDUPE=hash) against the same oracle on
                # every family, clean + corrupted — the randomized arm
                # of the dedupe parity matrix (tests/test_dedupe.py is
                # the deterministic pin)
                engines["sparse-hash"] = lambda: engine.check_encoded(
                    e, max_capacity=1 << 15, dedupe="hash")
                if seed == 0:
                    # the fused-frontier-kernel arm of the same matrix
                    # (tests/test_sparse_pallas.py is the deterministic
                    # pin). First seed only: every distinct (R, S, C)
                    # is its own interpret-kernel compile, and this
                    # tier rides tier-1's budget; capacity tiers past
                    # the kernel's VMEM gate degrade to the XLA hash
                    # transparently (note-tagged), which is itself the
                    # fallback contract under test
                    engines["sparse-hash-pallas"] = \
                        lambda: engine.check_encoded(
                            e, max_capacity=1 << 15, dedupe="hash",
                            sparse_pallas=True)
                if dense.fits_dense(dense.n_states(e), e.n_slots):
                    engines["dense"] = lambda: dense.check_encoded_dense(e)
                if bitdense.fits_bitdense(bitdense.n_states(e),
                                          e.n_slots):
                    engines["bitdense"] = \
                        lambda: bitdense.check_encoded_bitdense(e)
            for ename, fn in engines.items():
                try:
                    got = fn()["valid?"]
                except Exception:  # noqa: BLE001 — a crash IS a finding
                    failures.append((ename, name, seed, variant,
                                     "crash", traceback.format_exc()))
                    continue
                if got == "unknown":
                    continue    # engine hit its own budget: undecided
                runs += 1
                if got is not oracle:
                    failures.append((ename, name, seed, variant,
                                     f"oracle={oracle} got={got}", ""))
    assert not failures, failures
    assert runs > 0


@pytest.mark.fuzz
def test_fuzz_fake_device_invalid_ends_in_correct_verdict():
    """Randomized disagreement-escalation sweep (VERDICT r3 next#7): a
    fabricated device-invalid at a random fail event of a genuinely
    VALID history must end in the correct verdict via the host
    escalation ladder — never ship counterexample paths for a valid
    key. max_seeds covers the whole frontier so the surviving lineage
    is always sampled (the bounded default is sampling-dependent)."""
    from jepsen_tpu.models import CASRegister

    failures = []
    for seed in range(max(3, N_SEEDS)):
        rng = np.random.default_rng(1000 + seed)
        # alternate between the short-history whole-prefix branch
        # (<= 500 calls) and the windowed device-seeded branch (> 500
        # calls) — the latter is where a fabricated invalid could ship
        # fake paths from dead-end seeds, and where max_seeds matters.
        # The long size is FIXED so the frontier re-scan's compiled
        # shapes repeat across seeds (each distinct chunk length is a
        # fresh XLA CPU compile; random lengths made this tier crawl)
        long_branch = seed % 2 == 1
        n_ops = 1100 if long_branch else int(rng.integers(60, 140))
        # the long branch keeps crash_p low: every crashed call stays
        # an open slot forever, and ~30 open slots make the frontier
        # re-scan TPU-sized (capacity tiers to 2^20) — fine on a chip,
        # unaffordable in a CPU fuzz iteration
        h = rand_register_history(n_ops=n_ops, n_processes=4,
                                  n_values=3,
                                  crash_p=0.005 if long_branch else 0.03,
                                  fail_p=0.05, seed=2000 + seed)
        model = CASRegister()
        oracle = wgl.analysis(model, h, max_states=1_000_000,
                              deadline=monotonic() + 8)["valid?"]
        if oracle is not True:
            continue
        e = enc_mod.encode(model, h)
        n_samples = 1 if long_branch else min(3, e.n_returns)
        for fail_r in rng.choice(e.n_returns, size=n_samples,
                                 replace=False):
            r = engine.extract_final_paths(model, e, int(fail_r),
                                           max_seeds=4096)
            if r.get("valid?") is True:
                continue                      # overridden: correct
            if "final-paths-note" in r and not r.get("final-paths"):
                continue                      # indecisive, no fake paths
            failures.append((seed, int(fail_r), n_ops,
                             {k: r[k] for k in r
                              if k != "final-paths"}))
    assert not failures, failures


@pytest.mark.fuzz
def test_fuzz_sharded_hash_parity_on_mesh():
    """Randomized sort-vs-hash parity for the frontier-SHARDED engine
    on the 8-way CPU mesh: per-device open-addressed visited sets fed
    by the owner-routed all-to-all must land the exact sort-path
    result — verdict, failing op/event, max-frontier — on clean and
    value-corrupted histories (the order-independent pins; row order
    and configs-stepped differ by design)."""
    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import sharded

    mesh = Mesh(np.array(jax.devices()), ("frontier",))
    failures = []
    pin = lambda r: {k: r.get(k) for k in  # noqa: E731
                     ("valid?", "op", "fail-event", "max-frontier",
                      "capacity")}
    for seed in range(max(3, N_SEEDS)):
        # FIXED op count so compiled shapes repeat across seeds (each
        # distinct (R, C) is a fresh XLA CPU compile of the whole
        # sharded scan)
        h = rand_register_history(n_ops=48, n_processes=5, n_values=3,
                                  crash_p=0.06, fail_p=0.06,
                                  seed=4000 + seed)
        for variant in ("clean", "corrupt"):
            hv = h if variant == "clean" else corrupt_history(
                h, seed=seed, n_corruptions=2)
            e = enc_mod.encode(CASRegister(), hv)
            rs = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                               dedupe="sort")
            rh = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                               dedupe="hash")
            if pin(rs) != pin(rh) \
                    or rh.get("configs-stepped", 0) \
                    > rs.get("configs-stepped", 0):
                failures.append((seed, variant, rs, rh))
    assert not failures, failures


@pytest.mark.fuzz
def test_fuzz_elastic_engines_agree_with_wgl():
    """Randomized differential for the ISSUE 15 elastic layer: the
    stealing round executor (batched, key axis on the 8-way mesh) and
    the re-shard sharded ladder must agree with the host WGL oracle on
    clean and value-corrupted histories — scheduling and device
    recruiting must never touch a verdict. Fixed op counts so the
    compiled shapes repeat across seeds (the sharded-mesh sweep's
    precedent)."""
    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import elastic, engine, sharded

    mesh = Mesh(np.array(jax.devices()), ("key",))
    model = CASRegister()
    failures = []
    runs = 0
    for seed in range(max(2, N_SEEDS // 2)):
        hs, oracles = [], []
        for j in range(8):
            h = rand_register_history(n_ops=40, n_processes=5,
                                      n_values=3, crash_p=0.06,
                                      fail_p=0.06,
                                      seed=5000 + seed * 8 + j)
            if j % 2:
                h = corrupt_history(h, seed=j, n_corruptions=2)
            hs.append(h)
            oracles.append(wgl.analysis(
                model, h, max_states=1_000_000,
                deadline=monotonic() + 8)["valid?"])
        pre = [enc_mod.encode(model, h) for h in hs]
        rs = elastic.check_batch_stealing(model, pre, capacity=128,
                                          max_capacity=1 << 15,
                                          mesh=mesh)
        static = engine.check_batch_encoded(model, pre, capacity=128,
                                            max_capacity=1 << 15,
                                            mesh=mesh)
        for j, (r, s, oracle) in enumerate(zip(rs, static, oracles)):
            if oracle == "unknown" or r["valid?"] == "unknown":
                continue
            runs += 1
            if r["valid?"] is not oracle:
                failures.append(("steal-oracle", seed, j, oracle, r))
            if r["valid?"] != s["valid?"] \
                    or r.get("capacity") != s.get("capacity") \
                    or r.get("configs-stepped") != \
                    s.get("configs-stepped"):
                failures.append(("steal-static", seed, j, s, r))
        # the elastic sharded ladder vs the oracle on one key per seed
        e0 = pre[0]
        re = sharded.check_encoded_sharded_elastic(
            e0, mesh, capacity=64, max_capacity=1 << 15)
        if oracles[0] != "unknown" and re["valid?"] != "unknown":
            runs += 1
            if re["valid?"] is not oracles[0]:
                failures.append(("reshard-oracle", seed, oracles[0],
                                 re))
    assert not failures, failures
    assert runs > 0


@pytest.mark.fuzz
def test_fuzz_pallas_agrees_with_xla_closure():
    """Randomized pallas-vs-XLA-closure differential on kernel-
    supported shapes. The main fuzz loop's shapes sit below the pallas
    gate (C >= 12 means 2^12-config mask spaces, where the WGL oracle
    cannot terminate), so the kernel's fuzz oracle is the XLA while
    closure itself — the same algebra under a different execution,
    exactly the equivalence the r5 on-chip A/B correctness gate
    enforced. Verdicts AND fail events must match on clean (valid by
    construction) and value-corrupted variants; pallas now being the
    real-TPU default makes this the default-path fuzz."""
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import pallas_kernels as pk

    failures = []
    runs = 0
    for seed in range(max(3, N_SEEDS)):
        # FIXED op counts so compiled shapes repeat across seeds (each
        # distinct length is a fresh XLA CPU compile); k varies the
        # mask-space width across the kernel's W tiers
        n_ops = (48, 96, 144)[seed % 3]
        k = (11, 12)[seed % 2]
        for variant in ("clean", "corrupt"):
            h = adversarial_register_history(
                n_ops=n_ops, k_crashed=k, seed=3000 + seed)
            if variant == "corrupt":
                h = corrupt_history(h, seed=seed, n_corruptions=1)
            e = enc_mod.encode(CASRegister(), h)
            S, C = bitdense.n_states(e), max(5, e.n_slots)
            if not pk.supported(S, C):
                continue
            r_xla = bitdense.check_encoded_bitdense(
                e, use_pallas=False, closure_mode="while")
            r_pl = bitdense.check_encoded_bitdense(e, use_pallas=True)
            # guard vacuity: if the resolve logic ever downgrades an
            # explicit use_pallas=True, this would silently compare
            # xla against xla
            assert r_pl["closure"] == "pallas", r_pl
            runs += 1
            strip = lambda r: {k_: v for k_, v in r.items()  # noqa: E731
                               if k_ != "closure"}
            if strip(r_xla) != strip(r_pl):
                failures.append((seed, variant, n_ops, k, r_xla, r_pl))
    assert not failures, failures
    assert runs >= 2 * max(3, N_SEEDS) - 1, runs
