"""Compile-economics suite (ISSUE 17): shape canonicalization, the
AOT program registry, cache persistence, and the warm-handoff seam.

The load-bearing invariants:

- Representation never changes results: with JEPSEN_TPU_CANON_SHAPES
  armed (event rows quantized onto the EVENT_QUANTUM ladder) and with
  executables served from the JEPSEN_TPU_COMPILE_CACHE registry —
  in-memory or deserialized from disk — verdict, failing op/event,
  max-frontier, and configs-stepped are pinned identical to the
  flag-off path, per packable family, clean and corrupted.
- The cache DEGRADES, never lies: a stale jax version, a wrong shape
  key, truncated or unpicklable bytes each produce a counted
  ``engine.programs.load_errors`` plus a fresh compile with the right
  answer — never a crash, never a wrong program.
- A restarted replica with a populated cache serves its first delta
  with ZERO fresh compiles (the ledger proves it: compiles == 0,
  preloads >= 1) and a verdict bit-identical to the one-shot check.
"""

import os
import pickle

import numpy as np
import pytest

from jepsen_tpu.envflags import EnvFlagError
from jepsen_tpu.histories import (corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import encode as enc_mod, engine, programs
from jepsen_tpu.serve import CheckerService

PIN = ("valid?", "op", "fail-event", "max-frontier", "configs-stepped")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _oneshot(Model, ops, capacity=128):
    e = enc_mod.encode(Model(), History.wrap(list(ops)))
    return engine.check_encoded(e, capacity=capacity)


# same generators (and therefore the same compiled reference shapes)
# as tests/test_dedupe.py / tests/test_config_pack.py — the flag-off
# baselines here ride the jit cache those suites already warmed
FAMILIES = [
    ("cas-register", CASRegister,
     lambda: rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31)),
    ("gset", GSet,
     lambda: rand_gset_history(n_ops=36, n_processes=4, n_elements=9,
                               crash_p=0.06, seed=33)),
    ("uqueue", UnorderedQueue,
     lambda: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                crash_p=0.06, seed=34)),
    ("fifo", FIFOQueue,
     lambda: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                               crash_p=0.05, seed=35)),
]


@pytest.fixture(autouse=True)
def _fresh_registry(monkeypatch):
    """Every test starts flag-off with no process registry, and leaves
    none behind — the suite must not warm a later test's cache."""
    for var in ("JEPSEN_TPU_COMPILE_CACHE", "JEPSEN_TPU_CANON_SHAPES",
                "JEPSEN_TPU_PRECOMPILE"):
        monkeypatch.delenv(var, raising=False)
    programs.reset()
    yield monkeypatch
    programs.reset()


# ------------------------------------------------------- quantum math


def test_quantize_rows_ladder():
    assert programs.quantize_rows(1) == programs.EVENT_QUANTUM
    assert programs.quantize_rows(16) == 16
    assert programs.quantize_rows(17) == 32
    assert programs.quantize_rows(260) == 272
    # monotone, idempotent, never shrinks
    prev = 0
    for n in range(1, 200, 7):
        q = programs.quantize_rows(n)
        assert q >= n and q % programs.EVENT_QUANTUM == 0
        assert q >= prev
        assert programs.quantize_rows(q) == q
        prev = q


def test_population_counts_shrinks_under_canon():
    pop = programs.population_counts([100, 101, 112, 120, 260])
    assert pop["exact"] == 5
    # 100/101/112 -> 112, 120 -> 128, 260 -> 272
    assert pop["canon"] == 3
    assert programs.population_counts([]) == {"exact": 0, "canon": 0}


def test_pad_rows_fill_values_and_noop():
    xs = {"ev_slot": np.array([0, 1], np.int32),
          "f": np.array([[1, 2], [3, 4]], np.int32),
          "b": np.array([True, False])}
    out = programs.pad_rows(xs, 5)
    assert out["f"].shape == (5, 2) and out["b"].shape == (5,)
    assert (out["ev_slot"][2:] == -1).all()    # the scan-skip marker
    assert (out["f"][:2] == xs["f"]).all()
    assert (out["f"][2:] == -1).all()          # int pad rows are -1
    assert (out["b"][:2] == xs["b"]).all()
    assert not out["b"][2:].any()              # bool pad rows False
    same = programs.pad_rows(xs, 2)            # no-op: already there
    assert same["f"] is xs["f"]


# ---------------------------------------------------- flag validation


def test_flag_validation_fails_loud(_fresh_registry):
    mp = _fresh_registry
    mp.setenv("JEPSEN_TPU_CANON_SHAPES", "maybe")
    with pytest.raises(EnvFlagError):
        programs.canon_armed()
    mp.setenv("JEPSEN_TPU_PRECOMPILE", "yes")
    with pytest.raises(EnvFlagError):
        programs.precompile_armed()
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", "   ")
    with pytest.raises(EnvFlagError):
        programs.resolve_cache()


def test_flag_off_means_no_registry():
    assert programs.registry() is None
    # track() is a no-op, not an arm-by-side-effect
    programs.track("engine.check", {"x": np.zeros(3, np.int32)}, ("s",))
    assert programs.registry() is None


# ------------------------------------------------------ canon parity


@pytest.mark.parametrize("name,Model,gen", FAMILIES,
                         ids=[c[0] for c in FAMILIES])
def test_canon_parity_families(_fresh_registry, name, Model, gen):
    """Canonicalized shapes + registry dispatch == flag-off, bit for
    bit, on every pinned field."""
    ops = list(gen())
    base = _pin(_oneshot(Model, ops))
    mp = _fresh_registry
    mp.setenv("JEPSEN_TPU_CANON_SHAPES", "1")
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", "1")   # in-memory registry
    programs.reset()
    r = _oneshot(Model, ops)
    assert _pin(r) == base, name
    st = programs.registry().stats()
    assert st["misses"] >= 1 and st["compiles"] >= 1, st


def test_canon_parity_corrupted_and_mutex(_fresh_registry):
    """The invalid verdicts (a corrupted register stream, a mutex
    double-acquire) localize to the SAME op/event under the canon +
    registry path — padding must never shift the counterexample."""
    h = corrupt_history(FAMILIES[0][2](), seed=7, n_corruptions=2)
    mx = [invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
          invoke_op(1, "acquire", None), ok_op(1, "acquire", None)]
    base_r = _pin(_oneshot(CASRegister, list(h)))
    base_m = _pin(_oneshot(Mutex, mx, capacity=64))
    mp = _fresh_registry
    mp.setenv("JEPSEN_TPU_CANON_SHAPES", "1")
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", "1")
    programs.reset()
    assert _pin(_oneshot(CASRegister, list(h))) == base_r
    rm = _oneshot(Mutex, mx, capacity=64)
    assert rm["valid?"] is False
    assert _pin(rm) == base_m


def test_registry_hit_on_second_dispatch(_fresh_registry):
    mp = _fresh_registry
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", "1")
    programs.reset()
    ops = list(FAMILIES[0][2]())
    _oneshot(CASRegister, ops)
    st1 = programs.registry().stats()
    _oneshot(CASRegister, ops)
    st2 = programs.registry().stats()
    assert st2["hits"] > st1["hits"], (st1, st2)
    assert st2["compiles"] == st1["compiles"], (st1, st2)


# ------------------------------------------------- disk cache + safety


def _populate(tmp_path, mp, ops):
    """One checked run against a fresh on-disk cache; returns the
    cache dir and the baseline pin."""
    cache = str(tmp_path / "progcache")
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", cache)
    programs.reset()
    base = _pin(_oneshot(CASRegister, ops))
    st = programs.registry().stats()
    assert st["compiles"] >= 1, st
    jprogs = [f for f in os.listdir(cache) if f.endswith(".jprog")]
    assert jprogs, "no executable persisted"
    return cache, base


def test_cache_roundtrip_restart_zero_compiles(_fresh_registry,
                                               tmp_path):
    ops = list(FAMILIES[0][2]())
    cache, base = _populate(tmp_path, _fresh_registry, ops)
    programs.reset()                      # the process "restart"
    r = _oneshot(CASRegister, ops)
    st = programs.registry().stats()
    assert st["compiles"] == 0, st
    assert st["preloads"] >= 1, st
    assert st["load_errors"] == 0, st
    assert _pin(r) == base


def _corrupt(path, how):
    if how == "stale-version":
        with open(path, "rb") as fh:
            blob = pickle.load(fh)
        blob["fingerprint"]["jax"] = "0.0.0"
        with open(path, "wb") as fh:
            pickle.dump(blob, fh)
    elif how == "wrong-key":
        with open(path, "rb") as fh:
            blob = pickle.load(fh)
        blob["fingerprint"]["key"] = "deadbeef" * 4
        with open(path, "wb") as fh:
            pickle.dump(blob, fh)
    elif how == "truncated":
        raw = open(path, "rb").read()
        with open(path, "wb") as fh:
            fh.write(raw[: len(raw) // 2])
    elif how == "garbage":
        with open(path, "wb") as fh:
            fh.write(b"not a serialized executable")
    else:  # pragma: no cover
        raise AssertionError(how)


@pytest.mark.parametrize("how", ["stale-version", "wrong-key",
                                 "truncated", "garbage"])
def test_cache_load_degrades_never_lies(_fresh_registry, tmp_path,
                                        how):
    """Every corruption mode lands in the same place: counted
    load_errors, a fresh compile, the right answer."""
    ops = list(FAMILIES[0][2]())
    cache, base = _populate(tmp_path, _fresh_registry, ops)
    for f in os.listdir(cache):
        if f.endswith(".jprog"):
            _corrupt(os.path.join(cache, f), how)
    programs.reset()
    r = _oneshot(CASRegister, ops)
    st = programs.registry().stats()
    assert st["load_errors"] >= 1, (how, st)
    assert st["compiles"] >= 1, (how, st)
    assert st["preloads"] == 0, (how, st)
    assert _pin(r) == base, how


def test_torn_tmp_file_is_ignored(_fresh_registry, tmp_path):
    """A kill mid-persist leaves only a ``.tmp.<pid>`` file (the
    os.replace discipline); the loader must not even look at it."""
    ops = list(FAMILIES[0][2]())
    cache, base = _populate(tmp_path, _fresh_registry, ops)
    with open(os.path.join(cache, "0" * 32 + ".jprog.tmp.999"),
              "wb") as fh:
        fh.write(b"torn mid-write")
    programs.reset()
    r = _oneshot(CASRegister, ops)
    st = programs.registry().stats()
    assert st["load_errors"] == 0, st
    assert st["preloads"] >= 1 and st["compiles"] == 0, st
    assert _pin(r) == base


def test_swapped_cache_files_never_serve_wrong_program(
        _fresh_registry, tmp_path):
    """Two populated digests with their files swapped on disk: the
    fingerprint's embedded shape key catches both — two load_errors,
    two fresh compiles, both verdicts still right. (A run can persist
    more than two programs — the capacity ladder compiles one per
    rung — so swap the first two and leave the rest alone.)"""
    mp = _fresh_registry
    ops_a = list(FAMILIES[0][2]())
    ops_b = list(FAMILIES[1][2]())
    cache = str(tmp_path / "progcache")
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", cache)
    programs.reset()
    base_a = _pin(_oneshot(CASRegister, ops_a))
    base_b = _pin(_oneshot(GSet, ops_b))
    files = sorted(f for f in os.listdir(cache)
                   if f.endswith(".jprog"))
    assert len(files) >= 2, files
    pa, pb = (os.path.join(cache, f) for f in files[:2])
    tmp = pa + ".swap"
    os.replace(pa, tmp)
    os.replace(pb, pa)
    os.replace(tmp, pb)
    programs.reset()
    ra = _oneshot(CASRegister, ops_a)
    rb = _oneshot(GSet, ops_b)
    st = programs.registry().stats()
    assert st["load_errors"] >= 2, st
    assert st["compiles"] >= 2, st
    assert _pin(ra) == base_a and _pin(rb) == base_b


# --------------------------------------------- manifests + warm serve


def test_manifest_roundtrip_prewarms(_fresh_registry, tmp_path):
    """write_manifest -> (restart) -> warm_manifest pre-compiles the
    named programs from the shared disk cache, so the dispatch that
    follows is a pure hit."""
    mp = _fresh_registry
    ops = list(FAMILIES[0][2]())
    cache, base = _populate(tmp_path, mp, ops)
    reg = programs.registry()
    mpath = str(tmp_path / "k.programs.json")
    assert reg.write_manifest(mpath) >= 1
    programs.reset()
    reg2 = programs.registry()
    warmed = reg2.warm_manifest(mpath, engine.program_entries())
    assert warmed >= 1
    st = reg2.stats()
    assert st["manifest_warms"] >= 1 and st["compiles"] == 0, st
    r = _oneshot(CASRegister, ops)
    st2 = reg2.stats()
    assert st2["hits"] >= 1 and st2["compiles"] == 0, st2
    assert _pin(r) == base


def test_manifest_garbage_degrades(_fresh_registry, tmp_path):
    mp = _fresh_registry
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", str(tmp_path / "c"))
    programs.reset()
    bad = tmp_path / "bad.programs.json"
    bad.write_text("{not json")
    reg = programs.registry()
    assert reg.warm_manifest(str(bad),
                             engine.program_entries()) == 0
    assert reg.stats()["load_errors"] >= 1


def test_empty_registry_writes_no_manifest(_fresh_registry, tmp_path):
    mp = _fresh_registry
    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", "1")
    programs.reset()
    mpath = str(tmp_path / "empty.programs.json")
    assert programs.registry().write_manifest(mpath) == 0
    assert not os.path.exists(mpath)       # no file beats an empty one


# ------------------------------------------- restarted-replica pinned


def test_restarted_service_first_delta_zero_compiles(_fresh_registry,
                                                     tmp_path):
    """The serve-fleet acceptance pin: a replica restarted against a
    populated compile cache serves its FIRST post-restart delta (WAL
    replay included) with zero fresh compiles, and the final answer is
    bit-identical to the same delta stream fed flag-off with no
    restart. (The delta-fed pin is the session's own, not the
    one-shot's: on an escalating history the resumable scan legitimately
    steps fewer configs than a from-scratch check — the verdict is
    still cross-checked against the one-shot.)"""
    mp = _fresh_registry
    m = CASRegister()
    h = list(rand_register_history(n_ops=64, n_processes=5, n_values=3,
                                   crash_p=0.03, fail_p=0.05, seed=41))
    cuts = ((0, 16), (16, 32), (32, 48), (48, 64))

    # flag-off, single-process baseline
    ref_svc = CheckerService(m, wal_dir=str(tmp_path / "wal_ref"),
                             capacity=128)
    try:
        for a, b in cuts:
            ref_svc.submit("k", h[a:b], wait=True, timeout=120)
        base = _pin(ref_svc.finalize("k", timeout=120))
    finally:
        ref_svc.close()
    assert programs.registry() is None    # baseline really was flag-off

    mp.setenv("JEPSEN_TPU_COMPILE_CACHE", str(tmp_path / "progcache"))
    mp.setenv("JEPSEN_TPU_CANON_SHAPES", "1")
    programs.reset()
    wal = str(tmp_path / "wal")
    svc = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        for a, b in cuts[:3]:
            r = svc.submit("k", h[a:b], wait=True, timeout=120)
            assert "valid?" in r, r
    finally:
        svc.close()
    assert programs.registry().stats()["compiles"] >= 1

    programs.reset()                      # the replica "restart"
    svc2 = CheckerService(m, wal_dir=wal, capacity=128)
    try:
        a, b = cuts[3]
        r = svc2.submit("k", h[a:b], wait=True, timeout=120)
        assert "valid?" in r, r
        st = programs.registry().stats()
        assert st["compiles"] == 0, st
        assert st["preloads"] >= 1, st
        final = svc2.finalize("k", timeout=120)
    finally:
        svc2.close()
    assert _pin(final) == base
    assert final["valid?"] == _oneshot(CASRegister, h)["valid?"]
