"""The competition race (checker/competition.py): first decisive
verdict wins, losers are cooperatively cancelled, and — the point of
having a race at all — a wedged device arm cannot turn a check into a
hang (reference semantics: knossos competition/analysis, raced by
jepsen.checker's default linearizable analyzer, checker.clj:199)."""

import threading
import time

import numpy as np

from jepsen_tpu.checker import competition
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import CASRegister


def _h(*ops):
    return History.wrap(list(ops)).index()


def _valid_history(n=40):
    from jepsen_tpu.histories import rand_register_history
    return rand_register_history(n_ops=n, n_processes=4, crash_p=0.01,
                                 fail_p=0.05, seed=11)


def _invalid_history():
    return _h(invoke_op(0, "write", 1), ok_op(0, "write", 1),
              invoke_op(1, "read", None), ok_op(1, "read", 7))


def test_race_decisive_winner_and_fields():
    r = competition.analysis(CASRegister(), _valid_history())
    assert r["valid?"] is True
    assert r["analyzer"] in ("jax", "packed", "wgl")
    assert r["competition"]["winner"] == r["analyzer"]


def test_race_invalid_verdict_consistent():
    r = competition.analysis(CASRegister(), _invalid_history())
    assert r["valid?"] is False
    assert r["op"]["value"] == 7, r


def test_stalled_device_arm_still_yields_host_verdict(monkeypatch):
    """A deliberately-wedged jax arm (the TPU-tunnel outage mode: a
    device call that never returns and ignores Python signals) must not
    delay the race beyond the host arms' own runtime."""
    from jepsen_tpu.parallel import engine

    wedge = threading.Event()

    def wedged_analysis(model, history, **kw):
        wedge.wait(300)           # "forever" at test scale
        return {"valid?": "unknown", "error": "wedged"}

    monkeypatch.setattr(engine, "analysis", wedged_analysis)
    t0 = time.monotonic()
    r = competition.analysis(CASRegister(), _valid_history())
    elapsed = time.monotonic() - t0
    wedge.set()                   # unblock the daemon thread
    assert r["valid?"] is True
    assert r["analyzer"] in ("packed", "wgl")
    assert elapsed < 60, elapsed


def test_stalled_device_arm_through_dispatcher(monkeypatch):
    """Same hedge end-to-end through the "competition" algorithm of the
    linearizable checker (the default analyzer)."""
    from jepsen_tpu.parallel import engine

    wedge = threading.Event()

    def wedged_analysis(model, history, **kw):
        wedge.wait(300)
        return {"valid?": "unknown"}

    monkeypatch.setattr(engine, "analysis", wedged_analysis)
    r = linearizable(CASRegister()).check({}, _valid_history())
    wedge.set()
    assert r["valid?"] is True
    assert r["analyzer"] in ("packed", "wgl")
    assert r["competition"]["winner"] == r["analyzer"]


def test_losers_are_cancelled(monkeypatch):
    """When one arm decides, the cancel event must be visible to the
    others (cooperative future-cancel parity)."""
    from jepsen_tpu.checker import wgl

    seen = {}
    real = wgl.analysis

    def spying_wgl(model, history, max_states=50_000_000,
                   deadline=None, cancel=None):
        seen["cancel"] = cancel
        return real(model, history, max_states=max_states,
                    deadline=deadline, cancel=cancel)

    monkeypatch.setattr(wgl, "analysis", spying_wgl)
    r = competition.analysis(CASRegister(), _valid_history())
    assert r["valid?"] is True
    assert isinstance(seen["cancel"], threading.Event)
    # the race sets cancel once the winner is in (and again on return)
    assert seen["cancel"].is_set()


def test_cancelled_host_arm_reports_cancelled_not_timeout():
    """A cancelled host search must say "cancelled" — not masquerade
    as a deadline timeout (the fields feed race diagnostics)."""
    from jepsen_tpu.checker import linear_packed, wgl

    ev = threading.Event()
    ev.set()
    h = _valid_history(200)
    r = linear_packed.analysis(CASRegister(), h, cancel=ev)
    assert r["valid?"] == "unknown"
    assert r.get("error") == "cancelled"
    assert "timeout" not in r
    # wgl polls every 4096 explored states, so it needs a history that
    # actually backtracks (depth-first greedy sails through register
    # histories): a crashy FIFO key explores ~8.4k states (seed 5)
    from jepsen_tpu.histories import rand_fifo_history
    from jepsen_tpu.models import FIFOQueue
    ha = rand_fifo_history(n_ops=40, n_processes=6, n_values=3,
                           crash_p=0.25, seed=5)
    rw = wgl.analysis(FIFOQueue(), ha, cancel=ev)
    assert rw["valid?"] == "unknown"
    assert rw.get("error") == "cancelled"
    assert "timeout" not in rw
    # linear polls per return event — the arm raced for unpackable
    # models must carry the same contract
    from jepsen_tpu.checker import linear
    rl = linear.analysis(FIFOQueue(), ha, cancel=ev)
    assert rl["valid?"] == "unknown"
    assert rl.get("error") == "cancelled"
    assert "timeout" not in rl


def test_all_arms_indecisive_reports_unknown(monkeypatch):
    """When every arm is indecisive (crash/unknown), the race must
    return an honest "unknown" carrying the per-arm results."""
    from jepsen_tpu.parallel import engine
    from jepsen_tpu.checker import linear_packed, wgl

    monkeypatch.setattr(engine, "analysis",
                        lambda *a, **k: {"valid?": "unknown", "error": "x"})
    monkeypatch.setattr(linear_packed, "analysis",
                        lambda *a, **k: (_ for _ in ()).throw(
                            RuntimeError("packed crashed")))
    monkeypatch.setattr(wgl, "analysis",
                        lambda *a, **k: {"valid?": "unknown",
                                         "timeout": True})
    r = competition.analysis(CASRegister(), _valid_history())
    assert r["valid?"] == "unknown"
    assert r["competition"]["winner"] is None
    per_arm = r["competition"]["results"]
    assert set(per_arm) == {"jax", "packed", "wgl"}
    assert "packed crashed" in per_arm["packed"]["error"]


def test_race_timeout_returns_indecisive(monkeypatch):
    """With every arm stalled, `timeout` bounds the race."""
    from jepsen_tpu.parallel import engine
    from jepsen_tpu.checker import linear_packed, wgl

    wedge = threading.Event()

    def stall(*a, **k):
        wedge.wait(300)
        return {"valid?": "unknown"}

    monkeypatch.setattr(engine, "analysis", stall)
    monkeypatch.setattr(linear_packed, "analysis", stall)
    monkeypatch.setattr(wgl, "analysis", stall)
    t0 = time.monotonic()
    r = competition.analysis(CASRegister(), _valid_history(), timeout=1.0)
    elapsed = time.monotonic() - t0
    wedge.set()
    assert r["valid?"] == "unknown"
    assert "still running" in r["error"]
    assert elapsed < 30, elapsed


def test_unpackable_model_races_linear_vs_wgl():
    """Unpackable models fall back to the reference's exact race:
    linear vs wgl."""
    from jepsen_tpu.models import Model

    class Opaque(Model):
        """A register the packer doesn't know."""
        def __init__(self, v=None):
            self.v = v

        def step(self, op):
            if op.f == "write":
                return Opaque(op.value)
            if op.f == "read":
                if op.value is not None and op.value != self.v:
                    from jepsen_tpu.models import inconsistent
                    return inconsistent(f"read {op.value} != {self.v}")
                return self
            return self

        def __eq__(self, o):
            return isinstance(o, Opaque) and self.v == o.v

        def __hash__(self):
            return hash(("Opaque", self.v))

    r = linearizable(Opaque()).check({}, _h(
        invoke_op(0, "write", 2), ok_op(0, "write", 2),
        invoke_op(1, "read", None), ok_op(1, "read", 2)))
    assert r["valid?"] is True
    assert r["analyzer"] in ("linear", "wgl")
    assert r["competition"]["arms"] == ["linear", "wgl"]

def test_engine_probe_timeout_is_bounded(monkeypatch):
    """jax.devices() wedged in PJRT init (tunnel outage) must not hang
    the availability probe — it times out and reports unavailable."""
    import jax
    import importlib
    lz = importlib.import_module("jepsen_tpu.checker.linearizable")

    monkeypatch.setattr(lz, "_engine_probe_result", None)
    monkeypatch.setattr(lz, "_engine_probe", {})
    wedge = threading.Event()

    def hanging_devices(*a, **k):
        wedge.wait(300)
        return jax_real_devices()

    jax_real_devices = jax.devices
    monkeypatch.setattr(jax, "devices", hanging_devices)
    t0 = time.monotonic()
    ok = lz._engine_available(timeout=1.0)
    elapsed = time.monotonic() - t0
    assert ok is False
    assert elapsed < 30, elapsed
    # still unanswered: later calls peek at the SAME probe thread (no
    # new thread, no fresh full wait) and stay unavailable
    t0 = time.monotonic()
    assert lz._engine_available(timeout=300.0) is False
    assert time.monotonic() - t0 < 30
    assert len(lz._engine_probe) == 2          # one probe, reused
    # the probe finally answers (slow, not wedged): availability
    # RECOVERS — only actual answers are cached
    wedge.set()
    lz._engine_probe["thread"].join(30)
    assert lz._engine_available(timeout=1.0) is True
    assert lz._engine_probe_result is True


def test_unavailable_engine_races_host_arms_only(monkeypatch):
    """With the device runtime unavailable, packable models race
    packed vs wgl — no device thread is spawned to wedge."""
    import importlib
    lz = importlib.import_module("jepsen_tpu.checker.linearizable")

    monkeypatch.setattr(lz, "_engine_probe_result", False)
    r = lz.linearizable(CASRegister()).check({}, _valid_history())
    assert r["valid?"] is True
    assert r["competition"]["arms"] == ["packed", "wgl"]
    assert r["analyzer"] in ("packed", "wgl")

def test_no_timeout_race_bounded_once_hosts_report(monkeypatch):
    """Without an overall timeout, a wedged device arm plus indecisive
    host arms must not hang the race: once every host arm has reported,
    the wait for the device arm is bounded by DEVICE_ARM_GRACE_SECS."""
    from jepsen_tpu.parallel import engine
    from jepsen_tpu.checker import linear_packed, wgl

    monkeypatch.setattr(competition, "DEVICE_ARM_GRACE_SECS", 1.0)
    wedge = threading.Event()

    def wedged(*a, **k):
        wedge.wait(300)
        return {"valid?": "unknown"}

    monkeypatch.setattr(engine, "analysis", wedged)
    monkeypatch.setattr(linear_packed, "analysis",
                        lambda *a, **k: {"valid?": "unknown",
                                         "error": "config budget"})
    monkeypatch.setattr(wgl, "analysis",
                        lambda *a, **k: {"valid?": "unknown",
                                         "error": "state budget"})
    t0 = time.monotonic()
    r = competition.analysis(CASRegister(), _valid_history())
    elapsed = time.monotonic() - t0
    wedge.set()
    assert r["valid?"] == "unknown"
    assert "'jax'" in r["error"] and "still running" in r["error"]
    assert elapsed < 30, elapsed


def test_mid_process_wedge_skips_device_arm_recoverably(monkeypatch):
    """A device arm orphaned by an earlier race and silent since
    (tunnel died AFTER the availability probe cached healthy) must flip
    later competition checks to host arms only — no new wedged thread
    per check — and the suspicion must CLEAR when the arm finally
    reports (a slow-but-healthy device is not a wedge)."""
    import importlib
    lz = importlib.import_module("jepsen_tpu.checker.linearizable")

    monkeypatch.setattr(lz, "_engine_probe_result", True)
    # simulate: a device arm its race gave up on long ago, still silent
    ghost = threading.Thread(target=lambda: None)
    monkeypatch.setitem(competition._orphaned, ghost,
                        time.monotonic() - 1000.0)
    assert competition.device_engine_suspect() is True
    r = lz.linearizable(CASRegister()).check({}, _valid_history())
    assert r["valid?"] is True
    assert r["competition"]["arms"] == ["packed", "wgl"]
    # the arm finally reports (run_arm's finally pops it): suspicion
    # clears and the device arm rejoins the race
    with competition._device_arms_lock:
        competition._orphaned.pop(ghost, None)
    assert competition.device_engine_suspect() is False
    r2 = lz.linearizable(CASRegister()).check({}, _valid_history())
    assert r2["competition"]["arms"] == ["jax", "packed", "wgl"]


def test_orphaned_device_arm_registered_on_giveup(monkeypatch):
    """A race that stops waiting on its device arm must register the
    orphan that feeds the wedge detection."""
    from jepsen_tpu.parallel import engine

    wedge = threading.Event()

    def wedged(*a, **k):
        wedge.wait(300)
        return {"valid?": "unknown"}

    monkeypatch.setattr(engine, "analysis", wedged)
    with competition._device_arms_lock:
        before = set(competition._orphaned)
    r = competition.analysis(CASRegister(), _valid_history())
    assert r["valid?"] is True          # a host arm decided
    with competition._device_arms_lock:
        # set-difference, not a count: orphans left by OTHER tests'
        # races may be popped concurrently as their arms unwedge
        new = set(competition._orphaned) - before
    assert len(new) == 1, new
    wedge.set()                         # let the arm report and clean up


def test_decisive_verdict_posted_just_before_expiry_wins(monkeypatch):
    """On timeout expiry the race must drain already-posted results:
    a decisive verdict enqueued moments before the deadline beats
    "unknown"."""
    from jepsen_tpu.parallel import engine
    from jepsen_tpu.checker import linear_packed, wgl

    wedge = threading.Event()

    def stall(*a, **k):
        wedge.wait(300)
        return {"valid?": "unknown"}

    monkeypatch.setattr(engine, "analysis", stall)
    monkeypatch.setattr(linear_packed, "analysis", stall)

    def slow_decisive(*a, **k):
        time.sleep(0.7)
        return {"valid?": True}

    monkeypatch.setattr(wgl, "analysis", slow_decisive)
    r = competition.analysis(CASRegister(), _valid_history(), timeout=1.0)
    wedge.set()
    assert r["valid?"] is True
    assert r["analyzer"] == "wgl"
