"""MeshPlan (parallel.meshplan): the factored topology decision, the
re-shard ladder rungs, the multi-host key partition, and the gated
jax.distributed seam (ISSUE 15). The two-process localhost smoke is
slow-marked (it boots two fresh JAX processes)."""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from jepsen_tpu import envflags
from jepsen_tpu.parallel import meshplan
from jepsen_tpu.parallel.meshplan import MeshPlan


def test_topology_decision_matches_sharded_inline_logic():
    devs = np.array(jax.devices())
    # 1-D -> flat
    p = MeshPlan.from_mesh(Mesh(devs, ("frontier",)))
    assert not p.hierarchical and p.n_dev == devs.size
    assert p.mesh().axis_names == (meshplan.AXIS,)
    # 2-D both dims > 1 under the owner-routed exchange -> hierarchical
    p2 = MeshPlan.from_mesh(Mesh(devs.reshape(4, 2), ("a", "b")))
    assert p2.hierarchical and (p2.n_slice, p2.n_chip) == (4, 2)
    assert p2.mesh().axis_names == (meshplan.AX_SLICE,
                                    meshplan.AX_CHIP)
    # the all-gather A/B path always flattens (the historical rule)
    p3 = MeshPlan.from_mesh(Mesh(devs.reshape(4, 2), ("a", "b")),
                            exchange="gather")
    assert not p3.hierarchical
    # a degenerate 2-D (one dim = 1) flattens too
    p4 = MeshPlan.from_mesh(Mesh(devs.reshape(1, -1), ("a", "b")))
    assert not p4.hierarchical


def test_ladder_rungs_flat_and_hierarchical():
    devs = np.array(jax.devices())
    flat = MeshPlan(devs)
    assert [p.n_dev for p in flat.ladder(1)] == [1, 2, 4, 8]
    assert [p.n_dev for p in flat.ladder(2)] == [2, 4, 8]
    hier = MeshPlan(devs.reshape(4, 2), hierarchical=True)
    rungs = hier.ladder(1)
    assert [(p.n_dev, p.hierarchical) for p in rungs] \
        == [(1, False), (2, False), (4, True), (8, True)]
    # the last rung is always the full plan
    assert rungs[-1].n_dev == 8 and rungs[-1].hierarchical


def test_key_partition_deterministic_and_complete():
    p = MeshPlan(np.array(jax.devices()))
    keys = [f"k{i}" for i in range(40)] + [7, ("a", 1)]
    parts = p.key_partition(keys, n_parts=4)
    assert sorted((k for ks in parts.values() for k in ks),
                  key=repr) == sorted(keys, key=repr)
    # stable across calls and independent instances
    assert parts == MeshPlan(np.array(jax.devices())).key_partition(
        keys, n_parts=4)
    assert all(MeshPlan.key_home(k, 4) in range(4) for k in keys)


def test_host_slices_single_host():
    p = MeshPlan(np.array(jax.devices()))
    hs = p.host_slices()
    assert list(hs) == [0] and len(hs[0]) == p.n_dev
    assert p.local_devices() == hs[0]
    assert p.n_processes == 1


def test_distributed_init_gating(monkeypatch):
    # off/unset: a no-op, never touches jax.distributed
    monkeypatch.delenv("JEPSEN_TPU_DIST", raising=False)
    assert meshplan.distributed_init() is False
    # armed but half-configured: raise at the read site
    monkeypatch.setenv("JEPSEN_TPU_DIST", "1")
    for k in ("JEPSEN_TPU_DIST_COORD", "JEPSEN_TPU_DIST_NPROC",
              "JEPSEN_TPU_DIST_PROC"):
        monkeypatch.delenv(k, raising=False)
    with pytest.raises(envflags.EnvFlagError, match="DIST_COORD"):
        meshplan.distributed_init()
    monkeypatch.setenv("JEPSEN_TPU_DIST_COORD", "nocolon")
    monkeypatch.setenv("JEPSEN_TPU_DIST_NPROC", "2")
    monkeypatch.setenv("JEPSEN_TPU_DIST_PROC", "0")
    with pytest.raises(envflags.EnvFlagError, match="host:port"):
        meshplan.distributed_init()
    monkeypatch.setenv("JEPSEN_TPU_DIST_COORD", "127.0.0.1:0")
    monkeypatch.setenv("JEPSEN_TPU_DIST_PROC", "2")
    with pytest.raises(envflags.EnvFlagError, match="out of range"):
        meshplan.distributed_init()
    # bad flag value fails loudly, like every other knob
    monkeypatch.setenv("JEPSEN_TPU_DIST", "yes")
    with pytest.raises(envflags.EnvFlagError):
        meshplan.distributed_init()


_CHILD = r"""
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
from jepsen_tpu.parallel import meshplan
assert meshplan.distributed_init() is True
plan = meshplan.MeshPlan.auto()
parts = plan.key_partition([f"k{i}" for i in range(16)],
                           n_parts=plan.n_processes)
print(json.dumps({
    "proc": jax.process_index(),
    "n_proc": jax.process_count(),
    "global_devices": plan.n_dev,
    "local_devices": len(plan.local_devices()),
    "hosts": sorted(plan.host_slices()),
    "partition": {str(k): sorted(map(str, v))
                  for k, v in parts.items()},
}))
"""


@pytest.mark.slow
def test_distributed_two_process_localhost_smoke(tmp_path):
    """The DCN seam's smoke (ISSUE 15): two real processes complete
    the gated jax.distributed handshake over localhost CPU, see the
    union device set (2 hosts x 2 local devices), and compute the
    SAME independent-key partition without any coordination round —
    the property a pod-scale run relies on. Slow tier: boots two
    fresh JAX processes."""
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JEPSEN_TPU_DIST": "1",
        "JEPSEN_TPU_DIST_COORD": f"127.0.0.1:{port}",
        "JEPSEN_TPU_DIST_NPROC": "2",
    })
    procs = []
    for pid in range(2):
        e = dict(env)
        e["JEPSEN_TPU_DIST_PROC"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, (out, err)
        import json
        outs.append(json.loads(out.strip().splitlines()[-1]))
    assert sorted(o["proc"] for o in outs) == [0, 1]
    for o in outs:
        assert o["n_proc"] == 2
        assert o["global_devices"] == 4 and o["local_devices"] == 2
        assert o["hosts"] == [0, 1]
    # both processes computed the identical key partition — no
    # coordinator round needed to agree who checks what
    assert outs[0]["partition"] == outs[1]["partition"]
