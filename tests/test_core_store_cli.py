"""Orchestration, persistence, CLI, and web tests (reference:
jepsen/test/jepsen/core_test.clj with the dummy remote — SURVEY.md §4.5,
store_test.clj, cli semantics cli.clj:120-130)."""

import json
import os
import threading
import urllib.request

import pytest

import jepsen_tpu.generator as gen
from jepsen_tpu import cli as jcli
from jepsen_tpu import core as jcore
from jepsen_tpu import store as jstore
from jepsen_tpu import web as jweb
from jepsen_tpu.checker import linearizable
from jepsen_tpu.checker.core import FnChecker
from jepsen_tpu.history import History
from jepsen_tpu.models import CASRegister
from jepsen_tpu.workloads import AtomClient, linearizable_register


@pytest.fixture(autouse=True)
def store_in_tmp(tmp_path, monkeypatch):
    monkeypatch.setattr(jstore, "BASE_DIR", str(tmp_path / "store"))
    monkeypatch.chdir(tmp_path)
    yield


def register_test(**kw):
    t = jcore.make_test({
        "name": "register-test",
        "client": AtomClient(),
        "concurrency": 4,
        "generator": gen.clients(gen.limit(
            40, gen.mix([linearizable_register.r,
                         linearizable_register.w,
                         linearizable_register.cas]))),
        "checker": linearizable(CASRegister(), algorithm="wgl"),
    })
    t.update(kw)
    return t


def test_full_run_lifecycle():
    completed = jcore.run(register_test())
    assert completed["results"]["valid?"] is True
    h = completed["history"]
    assert len(h) == 80
    d = completed["store"].dir
    for f in ("history.edn", "history.txt", "test.json", "results.edn",
              "results.json", "jepsen.log"):
        assert os.path.exists(os.path.join(d, f)), f


def test_history_roundtrip_from_store():
    completed = jcore.run(register_test())
    d = completed["store"].dir
    h = History.load(os.path.join(d, "history.edn"))
    assert len(h) == len(completed["history"])
    r = linearizable(CASRegister(), algorithm="wgl").check({}, h)
    assert r["valid?"] is True


def test_store_latest_and_load():
    jcore.run(register_test())
    completed2 = jcore.run(register_test())
    latest = jstore.latest(jstore.BASE_DIR)
    assert latest is not None
    assert os.path.realpath(latest) == os.path.realpath(
        completed2["store"].dir)
    loaded = jstore.load_run(latest)
    assert loaded["results"]["valid?"] is True
    assert loaded["test"]["name"] == "register-test"
    # live objects are stripped from the stored test
    assert "client" not in loaded["test"]

    # jepsen.repl/last-test analogue rides the same store
    from jepsen_tpu import repl
    for by_name in (None, "register-test"):
        run = repl.last_test(by_name)
        assert run is not None
        assert os.path.realpath(run["dir"]) == os.path.realpath(latest)
        assert run["results"]["valid?"] is True
    assert repl.last_test("no-such-test") is None

    # names are sanitized on write; lookup must apply the same rule
    jcore.run(register_test(name="etcd/cas"))
    run = repl.last_test("etcd/cas")
    assert run is not None and run["test"]["name"] == "etcd/cas"


def test_checker_crash_yields_unknown():
    def boom(test, history, opts):
        raise RuntimeError("checker exploded")

    completed = jcore.run(register_test(checker=FnChecker(boom)))
    assert completed["results"]["valid?"] == "unknown"
    assert "checker exploded" in completed["results"]["error"]
    # history survived the checker crash (save-1 before analyze)
    assert os.path.exists(
        os.path.join(completed["store"].dir, "history.edn"))


def test_concurrency_parse():
    assert jcli.parse_concurrency("10", 5) == 10
    assert jcli.parse_concurrency("3n", 5) == 15
    assert jcli.parse_concurrency("n", 5) == 5


def _register_test_fn(opts):
    return jcore.make_test({
        "name": "cli-register",
        "nodes": opts["nodes"],
        "concurrency": opts["concurrency"],
        "client": AtomClient(),
        "generator": gen.clients(gen.limit(
            30, gen.mix([linearizable_register.r,
                         linearizable_register.w]))),
        "checker": linearizable(CASRegister(), algorithm="wgl"),
    })


def test_cli_test_and_analyze(capsys):
    code = jcli.run_cli(_register_test_fn,
                        ["test", "--no-ssh", "--concurrency", "2"])
    assert code == jcli.EXIT_VALID
    out = capsys.readouterr().out
    assert json.loads(out.splitlines()[-1])["valid?"] is True

    code = jcli.run_cli(_register_test_fn, ["analyze", "--no-ssh"])
    assert code == jcli.EXIT_VALID


def test_cli_invalid_exit_code():
    def bad_test_fn(opts):
        t = _register_test_fn(opts)
        t["checker"] = FnChecker(lambda *a: {"valid?": False})
        return t

    code = jcli.run_cli(bad_test_fn, ["test", "--no-ssh"])
    assert code == jcli.EXIT_INVALID


def test_cli_test_all_sweep(capsys):
    """test-all runs the whole sweep, collates outcomes, prints the
    summary sections, and exits with the worst outcome (cli.clj:478-503
    test-all-cmd, test-all-exit!: crashed > unknown > invalid > valid)."""
    def switching_test_fn(opts):
        t = _register_test_fn(opts)
        t["name"] = f"sweep-{opts.get('workload')}-{opts.get('nemesis')}"
        if opts.get("workload") == "bad":
            t["checker"] = FnChecker(lambda *a: {"valid?": False})
        return t

    code = jcli.run_cli(switching_test_fn,
                        ["test-all", "--no-ssh",
                         "--workloads", "good,bad",
                         "--nemeses", "none"])
    out = capsys.readouterr().out
    assert code == jcli.EXIT_INVALID
    assert "1 successes" in out and "1 failures" in out
    assert "# Failed tests" in out

    code = jcli.run_cli(switching_test_fn,
                        ["test-all", "--no-ssh", "--workloads", "good"])
    assert code == jcli.EXIT_VALID

    # a crashing test map must not end the sweep, and wins the exit code
    def crashing_test_fn(opts):
        if opts.get("workload") == "boom":
            raise RuntimeError("kaboom")
        return switching_test_fn(opts)

    code = jcli.run_cli(crashing_test_fn,
                        ["test-all", "--no-ssh",
                         "--workloads", "good,boom,bad"])
    assert code == jcli.EXIT_CRASH
    out = capsys.readouterr().out
    assert "1 crashed" in out and "1 successes" in out


def test_cli_unknown_exit_code():
    def unk_test_fn(opts):
        t = _register_test_fn(opts)
        t["checker"] = FnChecker(lambda *a: {"valid?": "unknown"})
        return t

    code = jcli.run_cli(unk_test_fn, ["test", "--no-ssh"])
    assert code == jcli.EXIT_UNKNOWN


def test_cli_bad_args():
    assert jcli.run_cli(None, []) == jcli.EXIT_BAD_ARGS


def test_web_browser():
    completed = jcore.run(register_test())
    srv = jweb.make_server(base_dir=jstore.BASE_DIR)
    th = threading.Thread(target=srv.serve_forever, daemon=True)
    th.start()
    try:
        port = srv.server_address[1]
        home = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/").read().decode()
        assert "register-test" in home
        ts = os.path.basename(completed["store"].dir)
        hist = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/files/register-test/{ts}/history.txt"
        ).read().decode()
        assert "invoke" in hist
        z = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/zip/register-test/{ts}").read()
        assert z[:2] == b"PK"
        # path traversal denied
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/files/../../etc/passwd")
    finally:
        srv.shutdown()


def test_register_workload_end_to_end():
    wl = linearizable_register.workload(
        {"ops-per-key": 10, "algorithm": "wgl"})
    t = jcore.make_test({
        "name": "lin-reg",
        "concurrency": 4,
        "client": _KeyedAtomClient(),
        "generator": gen.time_limit(2, wl["generator"]),
        "checker": wl["checker"],
    })
    completed = jcore.run(t)
    assert completed["results"]["valid?"] is True
    lin = completed["results"]["linear"]
    assert len(lin["results"]) >= 2  # several keys exercised


class _KeyedAtomClient(AtomClient):
    """AtomClient over KV-tuple values: one register per key."""

    def __init__(self, data=None, lock=None):
        self.data = data if data is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return _KeyedAtomClient(self.data, self.lock)

    def invoke(self, test, op):
        from jepsen_tpu.history import Op
        from jepsen_tpu.independent import KV
        o = Op(op)
        k, v = op["value"]
        f = op.get("f")
        with self.lock:
            cur = self.data.get(k)
            if f == "read":
                o["type"] = "ok"
                o["value"] = KV(k, cur)
            elif f == "write":
                self.data[k] = v
                o["type"] = "ok"
            elif f == "cas":
                old, new = v
                if cur == old:
                    self.data[k] = new
                    o["type"] = "ok"
                else:
                    o["type"] = "fail"
        return o

