from jepsen_tpu import models
from jepsen_tpu.history import Intern, Op
from jepsen_tpu.models import (
    CASRegister, FIFOQueue, GSet, Mutex, Register, UnorderedQueue,
    is_inconsistent,
)


def step(m, f, value=None):
    return m.step(Op(f=f, value=value))


def test_register():
    m = Register()
    m = step(m, "write", 3)
    assert m == Register(3)
    assert step(m, "read", 3) == m
    assert is_inconsistent(step(m, "read", 4))
    assert step(m, "read", None) == m  # unknown read is a wildcard


def test_cas_register():
    m = CASRegister(1)
    assert step(m, "cas", [1, 2]) == CASRegister(2)
    assert is_inconsistent(step(m, "cas", [3, 4]))
    assert step(m, "write", 9) == CASRegister(9)
    assert is_inconsistent(step(m, "read", 2))
    assert step(m, "read", 1) == m


def test_mutex():
    m = Mutex()
    m2 = step(m, "acquire")
    assert m2 == Mutex(True)
    assert is_inconsistent(step(m2, "acquire"))
    assert step(m2, "release") == Mutex(False)
    assert is_inconsistent(step(m, "release"))


def test_unordered_queue():
    m = UnorderedQueue()
    m = step(m, "enqueue", 1)
    m = step(m, "enqueue", 2)
    m2 = step(m, "dequeue", 2)  # out of order is fine
    assert not is_inconsistent(m2)
    assert is_inconsistent(step(m2, "dequeue", 2))
    assert not is_inconsistent(step(m2, "dequeue", 1))
    # multiset: duplicate elements
    m3 = step(step(m, "enqueue", 1), "dequeue", 1)
    assert not is_inconsistent(step(m3, "dequeue", 1))


def test_fifo_queue():
    m = FIFOQueue()
    m = step(m, "enqueue", 1)
    m = step(m, "enqueue", 2)
    assert is_inconsistent(step(m, "dequeue", 2))
    m = step(m, "dequeue", 1)
    assert not is_inconsistent(step(m, "dequeue", 2))


def test_gset():
    m = GSet()
    m = step(m, "add", 1)
    m = step(m, "add", 2)
    assert not is_inconsistent(step(m, "read", [1, 2]))
    assert is_inconsistent(step(m, "read", [1]))
    assert not is_inconsistent(step(m, "read", None))


def test_pack_spec_register():
    intern = Intern()
    spec = models.pack_spec(CASRegister(), intern)
    assert spec is not None
    assert spec.state0 == -1  # nil
    f, a0, a1, wild = spec.encode_call("cas", [1, 2], None, False)
    assert f == models.F_CAS and not wild
    assert intern.value(a0) == 1 and intern.value(a1) == 2
    f, a0, a1, wild = spec.encode_call("read", None, 5, False)
    assert f == models.F_READ and intern.value(a0) == 5
    f, a0, a1, wild = spec.encode_call("read", None, None, True)
    assert wild


def test_pack_spec_unpackable():
    class Custom(models.Model):  # user-defined model: host-only
        def step(self, op):
            return self

    assert models.pack_spec(Custom(), Intern()) is None


def test_pack_spec_gset_uqueue_fifo_pack():
    # round 3: gset, unordered-queue and fifo-queue gained device tiers
    from jepsen_tpu.models import FIFOQueue
    assert models.pack_spec(GSet(), Intern()).step_name == "gset"
    assert models.pack_spec(UnorderedQueue(), Intern()).step_name == "uqueue"
    assert models.pack_spec(FIFOQueue(), Intern()).step_name == "fifo"
