"""Elastic mesh scheduling (ISSUE 15): the skew-driven key
work-stealer (JEPSEN_TPU_STEAL, parallel.elastic), the
re-shard-on-escalation ladder (JEPSEN_TPU_RESHARD,
sharded.check_encoded_sharded_elastic), and the serve/stream key
migration primitives. The deterministic parity suite rides tier-1; the
forced-skew wall-clock A/B and the 2-D promotion integration are
slow-marked (minutes of sparse CPU searches — the fast pins here cover
the same code paths at small shapes)."""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from jepsen_tpu import envflags
from jepsen_tpu.histories import (adversarial_register_history,
                                  corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import elastic, encode as enc_mod, engine
from jepsen_tpu.parallel import sharded
from jepsen_tpu.parallel.elastic import KeyScheduler

# the order-independent result fields that must not move under any
# scheduling decision (the ISSUE 15 parity pin set)
PIN = elastic.STEAL_PIN


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _mesh():
    return Mesh(np.array(jax.devices()), ("key",))


# ------------------------------------------------------ scheduler unit


def test_scheduler_static_placement_and_rounds():
    """Seed queues are contiguous blocks (the static sharded key-axis
    placement) and rounds issue device-major; with steal=False nothing
    ever migrates."""
    s = KeyScheduler(list(range(8)), n_dev=4, round_keys=1,
                     steal=False)
    assert [list(q) for q in s.queues] == [[0, 1], [2, 3], [4, 5],
                                           [6, 7]]
    p1 = s.next_round()
    assert p1 == [(0, 0), (2, 1), (4, 2), (6, 3)]
    s.observe({0: 100.0, 2: 1.0, 4: 1.0, 6: 1.0}, p1)
    # static: the hot cohort's remaining key stays home
    assert [list(q) for q in s.queues] == [[1], [3], [5], [7]]
    assert s.steals == 0
    p2 = s.next_round()
    assert p2 == [(1, 0), (3, 1), (5, 2), (7, 3)]
    assert s.next_round() is None
    st = s.stats()
    assert st["rounds"] == 2 and st["steals"] == 0
    assert st["per_device_cost"][0] == 100.0


def test_scheduler_rebalance_concentrates_hot_cohort():
    """After observing one hot cohort, the stealer deals the pending
    keys back round-major by predicted cost: the hot device's backlog
    spreads across ALL devices into the earliest rounds instead of
    straggling one lane per round."""
    # device 0's cohort = keys 0..3 (heavy), rest light
    s = KeyScheduler(list(range(16)), n_dev=4, round_keys=1)
    p1 = s.next_round()
    assert p1 == [(0, 0), (4, 1), (8, 2), (12, 3)]
    s.observe({0: 100.0, 4: 1.0, 8: 1.0, 12: 1.0}, p1)
    # pending heavy keys 1,2,3 (cohort 0, predicted 100) must fill the
    # NEXT round together, spread over devices
    p2 = s.next_round()
    assert [i for i, _d in p2][:3] == [1, 2, 3]
    assert s.steals > 0
    st = s.stats()
    assert st["cohort_pred"][0] == 100.0
    # deterministic: same observations -> same placement
    s2 = KeyScheduler(list(range(16)), n_dev=4, round_keys=1)
    q1 = s2.next_round()
    s2.observe({0: 100.0, 4: 1.0, 8: 1.0, 12: 1.0}, q1)
    assert s2.next_round() == p2


def test_scheduler_unobserved_keeps_static_placement():
    """No cost signal (e.g. a bitdense bucket with search stats off)
    means no rebalancing — never fabricate a prediction."""
    s = KeyScheduler(list(range(8)), n_dev=4, round_keys=1)
    p1 = s.next_round()
    s.observe({}, p1)
    assert s.next_round() == [(1, 0), (3, 1), (5, 2), (7, 3)]
    assert s.steals == 0


def test_key_cost_signal_preference():
    # stats block wins over counters; counters over nothing
    assert elastic.key_cost({"capacity": 64, "configs-stepped": 10},
                            64) == 64 + 10
    tiered = elastic.key_cost(
        {"capacity": 256, "configs-stepped": 10}, 64)
    assert tiered == 3 * 256 + 10      # two doublings -> 3x weight
    with_stats = elastic.key_cost(
        {"capacity": 64, "configs-stepped": 10,
         "stats": {"closure-iters": [2, 3]}}, 64)
    assert with_stats == 64 * (5 + 2)
    assert elastic.key_cost({"valid?": True}, 64) is None


# ---------------------------------------------------- parity (tier-1)


FAMILIES = [
    ("cas-register", CASRegister,
     lambda: rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31)),
    ("gset", GSet,
     lambda: rand_gset_history(n_ops=36, n_processes=4, n_elements=9,
                               crash_p=0.06, seed=33)),
    ("uqueue", UnorderedQueue,
     lambda: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                crash_p=0.06, seed=34)),
    ("fifo", FIFOQueue,
     lambda: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                               crash_p=0.05, seed=35)),
]


@pytest.mark.parametrize("name,Model,gen", FAMILIES,
                         ids=[c[0] for c in FAMILIES])
@pytest.mark.parametrize("dedupe", ["sort", "hash"])
def test_steal_parity_clean_and_corrupted(name, Model, gen, dedupe):
    """The ISSUE 15 parity pin: stealing on vs off (and vs the static
    executor) is bit-identical in verdict/op/fail-event/max-frontier/
    capacity/configs-stepped across the packable families,
    clean+corrupted, both dedupe strategies."""
    h = gen()
    model = Model()
    pres = []
    for variant in (h, corrupt_history(h, seed=7, n_corruptions=2)):
        try:
            pres.append(enc_mod.encode(model, variant))
        except enc_mod.EncodeError:
            continue
    if not pres:
        pytest.skip("family/shape not device-encodable")
    # a batch wide enough for two rounds on the 8-way mesh
    # K=8 exactly: divisible by the mesh so no ragged replicated
    # round compiles its own program (compile budget, not semantics)
    pre = (pres * 8)[:8]
    mesh = _mesh()
    ref = engine.check_batch_encoded(model, pre, capacity=128,
                                     mesh=mesh, dedupe=dedupe)
    on = elastic.check_batch_stealing(model, pre, capacity=128,
                                      mesh=mesh, dedupe=dedupe)
    off = elastic.check_batch_stealing(model, pre, capacity=128,
                                       mesh=mesh, dedupe=dedupe,
                                       steal=False)
    assert [_pin(r) for r in on] == [_pin(r) for r in ref]
    assert [_pin(r) for r in off] == [_pin(r) for r in ref]


def test_steal_parity_mutex_invalid_and_packed():
    """Invalid verdicts and the packed configuration word through the
    stealer: same counterexample localization, packed + unpacked."""
    from jepsen_tpu.history import History, invoke_op, ok_op
    h = History.wrap([
        invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
        invoke_op(1, "acquire", None), ok_op(1, "acquire", None),
    ]).index()
    e = enc_mod.encode(Mutex(), h)
    pre = [e] * 8
    mesh = _mesh()
    for pack in (False, True):
        ref = engine.check_batch_encoded(Mutex(), pre, capacity=64,
                                         mesh=mesh, config_pack=pack)
        got = elastic.check_batch_stealing(Mutex(), pre, capacity=64,
                                           mesh=mesh, config_pack=pack)
        assert [_pin(r) for r in got] == [_pin(r) for r in ref]
        assert got[0]["valid?"] is False


def test_steal_capacity_ladder_parity_per_key():
    """Per-key capacities are placement-independent: a heavy key lands
    the same escalated tier whether it shares its round with light
    keys or not (the round executor's ladder is the contract twin of
    _check_batch_sparse's)."""
    model, hs = elastic.forced_skew_histories(n_heavy=2, n_light=6)
    pre = [enc_mod.encode(model, h) for h in hs]
    mesh = _mesh()
    ref = engine.check_batch_encoded(model, pre,
                                     capacity=elastic.SKEW_CAPACITY,
                                     max_capacity=1 << 16, mesh=mesh)
    st: dict = {}
    got = elastic.check_batch_stealing(model, pre,
                                       capacity=elastic.SKEW_CAPACITY,
                                       max_capacity=1 << 16, mesh=mesh,
                                       stats=st)
    assert [_pin(r) for r in got] == [_pin(r) for r in ref]
    # the heavy keys really escalated (otherwise this pins nothing)
    assert max(r["capacity"] for r in got) > elastic.SKEW_CAPACITY
    assert st["buckets"][0]["engine"] == "sparse"


def test_check_batch_steal_routing_and_stats_guard():
    model = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=4, n_values=3,
                                crash_p=0.05, seed=40 + i)
          for i in range(8)]
    mesh = _mesh()
    ref = engine.check_batch(model, hs, mesh=mesh)
    st: dict = {}
    got = engine.check_batch(model, hs, mesh=mesh, steal=True,
                             steal_stats=st)
    assert [_pin(r) for r in got] == [_pin(r) for r in ref]
    assert st["steal"] is True and st["buckets"]
    # the loud-misuse contract (the cache/pipeline_stats precedent) —
    # on BOTH routes: the pipelined path must not silently leave the
    # dict empty either
    with pytest.raises(ValueError, match="steal_stats"):
        engine.check_batch(model, hs, steal_stats={})
    with pytest.raises(ValueError, match="steal_stats"):
        engine.check_batch(model, hs, pipeline=True, cache=False,
                           steal_stats={})
    # ragged batches (K not a device multiple) stay parity-identical:
    # scheduler rounds pad to alignment with discarded duplicate lanes
    ragged = hs[:5]
    ref_r = engine.check_batch(model, ragged, mesh=mesh)
    got_r = engine.check_batch(model, ragged, mesh=mesh, steal=True)
    assert [_pin(r) for r in got_r] == [_pin(r) for r in ref_r]


def test_steal_env_flag_resolution(monkeypatch):
    monkeypatch.delenv("JEPSEN_TPU_STEAL", raising=False)
    assert engine._resolve_steal(None) is False
    monkeypatch.setenv("JEPSEN_TPU_STEAL", "1")
    assert engine._resolve_steal(None) is True
    monkeypatch.setenv("JEPSEN_TPU_STEAL", "yes")
    with pytest.raises(envflags.EnvFlagError):
        engine._resolve_steal(None)
    monkeypatch.delenv("JEPSEN_TPU_STEAL", raising=False)
    monkeypatch.setenv("JEPSEN_TPU_RESHARD", "2")
    with pytest.raises(envflags.EnvFlagError):
        engine._resolve_reshard(None)
    monkeypatch.delenv("JEPSEN_TPU_RESHARD", raising=False)
    monkeypatch.setenv("JEPSEN_TPU_STEAL_ROUND", "0")
    with pytest.raises(envflags.EnvFlagError):
        elastic._resolve_round_keys(0)
    monkeypatch.setenv("JEPSEN_TPU_STEAL_ROUND", "3")
    assert elastic._resolve_round_keys(0) == 3
    assert elastic._resolve_round_keys(5) == 5   # explicit arg wins


# ----------------------------------------------------------- re-shard

# ONE adversarial shape shared by every re-shard test, and the static
# + elastic results computed once per session: sharded shard_map
# programs are the suite's most expensive CPU compiles, so the tests
# below assert different contracts against the same two runs.


@pytest.fixture(scope="module")
def reshard_runs():
    h = adversarial_register_history(n_ops=60, k_crashed=6, seed=7)
    e = enc_mod.encode(CASRegister(), h)
    mesh = _mesh()
    r_static = sharded.check_encoded_sharded(e, mesh, capacity=128,
                                             max_capacity=1 << 16)
    r_el = sharded.check_encoded_sharded_elastic(
        e, mesh, capacity=128, max_capacity=1 << 16)
    return e, mesh, r_static, r_el


def test_reshard_recruits_devices_with_identical_verdict(reshard_runs):
    """The elastic ladder answers overflow by recruiting devices at
    flat per-device capacity; verdict fields match the grow-the-table
    ladder and the rung trail is recorded."""
    _e, _mesh_, r_static, r_el = reshard_runs
    keys = ("valid?", "op", "fail-event", "max-frontier")
    assert {k: r_static.get(k) for k in keys} \
        == {k: r_el.get(k) for k in keys}
    trail = r_el["reshard"]
    assert trail["start-devices"] == 2
    assert trail["events"], r_el
    # every rung recruited more devices; per-device capacity flat
    devs = [trail["start-devices"]] + [ev["devices"][1]
                                       for ev in trail["events"]]
    assert devs == sorted(devs) and len(set(devs)) == len(devs)
    assert r_el["devices"] == devs[-1]
    # the static result never carries the key: flag-off schema parity
    assert "reshard" not in r_static


def test_reshard_flag_delegation(reshard_runs, monkeypatch):
    """check_encoded_sharded(reshard=True) delegates to the elastic
    ladder (same rungs as calling it directly); unset env keeps the
    plain ladder."""
    e, mesh, r_static, r_el = reshard_runs
    monkeypatch.delenv("JEPSEN_TPU_RESHARD", raising=False)
    r = sharded.check_encoded_sharded(e, mesh, capacity=128,
                                      max_capacity=1 << 16,
                                      reshard=True)
    assert r.get("reshard") == r_el["reshard"]
    assert r["valid?"] == r_static["valid?"]


def test_reshard_escalation_tier(reshard_runs):
    """A batch-overflow key escalating through _escalate_overflow with
    reshard on lands the same verdict as the static escalation, with
    the elastic sharded tier behind it."""
    e, mesh, r_static, _r_el = reshard_runs
    ref = engine._escalate_overflow(e, 64, mesh)
    got = engine._escalate_overflow(e, 64, mesh, reshard=True)
    assert ref["valid?"] == got["valid?"] == r_static["valid?"]
    assert got["escalated"] in ("single", "sharded")


def test_reshard_overflow_at_full_mesh_stays_unknown(reshard_runs):
    """Ceilings and overflow semantics unchanged: a shape the full
    recruited mesh still cannot hold lands the same structured
    unknown. max_capacity=512 reuses the shared runs' compiled rung
    shapes (128@2 -> 256@4 -> 512@8) — the next doubling is refused."""
    e, mesh, _r_static, r_el = reshard_runs
    # only meaningful if the shared shape really outgrows 512
    assert r_el["capacity"] > 512
    r = sharded.check_encoded_sharded_elastic(e, mesh, capacity=128,
                                              max_capacity=512)
    assert r["valid?"] == "unknown"
    assert "frontier overflow" in r["error"]
    assert r["reshard"]["events"]          # it did try recruiting


# ------------------------------------------- serve / session migration


def test_session_migrate_bit_identical():
    """HistorySession.migrate between devices mid-stream: the
    canonical checkpoint is host-side, so the next delta resumes on
    the new device bit-identically to an unmigrated session."""
    from jepsen_tpu.parallel import extend as ext
    h = list(rand_register_history(n_ops=24, n_processes=4, n_values=3,
                                   crash_p=0.05, seed=21))
    devs = jax.devices()
    model = CASRegister()

    def run(migrate):
        s = ext.HistorySession(model, capacity=128,
                               device=devs[0], key="k")
        s.extend(h[:12])
        r1 = s.check()
        if migrate:
            s.migrate(devs[-1])
            assert s.device is devs[-1]
        s.extend(h[12:])
        return r1, s.check()

    (a1, a2), (b1, b2) = run(False), run(True)
    assert _pin(a1) == _pin(b1) and _pin(a2) == _pin(b2)


def test_serve_steal_key_freeze_thaw_migration(tmp_path):
    """CheckerService.steal_key: the mid-stream serve migration —
    freeze through the eviction path (WAL/checkpoint store), re-pin
    the device, thaw on the next delta; finals bit-identical to the
    unmigrated stream. Also the in-memory variant (no WAL) via
    HistorySession.migrate."""
    from jepsen_tpu.serve.service import CheckerService
    h = list(rand_register_history(n_ops=24, n_processes=4, n_values=3,
                                   crash_p=0.05, seed=22))
    model = CASRegister()
    devs = jax.devices()

    def run(wal_dir, steal_to):
        svc = CheckerService(model, wal_dir=wal_dir, capacity=128)
        try:
            svc.submit("k", h[:12], wait=True, timeout=120)
            assert svc.drain(timeout=60)
            if steal_to is not None:
                assert svc.steal_key("k", steal_to) is True
                ks = svc._keys["k"]
                assert ks.device is steal_to
                if wal_dir is not None:
                    assert ks.session is None   # frozen, thaws on next
            svc.submit("k", h[12:], wait=True, timeout=120)
            f = svc.finalize("k", timeout=120)
            if steal_to is not None:
                sess = svc._keys["k"].session
                assert sess is not None and sess.device is steal_to
        finally:
            svc.close()
        return f

    base = run(str(tmp_path / "w0"), None)
    stolen = run(str(tmp_path / "w1"), devs[-1])
    in_mem = run(None, devs[-1])
    assert _pin(stolen) == _pin(base)
    assert _pin(in_mem) == _pin(base)


def test_serve_steal_key_refuses_with_pending_work(tmp_path):
    from jepsen_tpu.serve.service import CheckerService
    model = CASRegister()
    h = list(rand_register_history(n_ops=12, n_processes=3, n_values=3,
                                   seed=23))
    svc = CheckerService(model, wal_dir=str(tmp_path / "w"),
                         capacity=128, start_worker=False)
    try:
        assert svc.steal_key("missing") is False
        svc.submit("k", h, seq=1)
        # worker never ran: the delta is still pending — refuse
        assert svc.steal_key("k", jax.devices()[-1]) is False
    finally:
        # no worker: a draining close would wait on the pending
        # delta forever
        svc.close(drain=False)


# ------------------------------------------------- report skew column


def test_search_report_device_skew_column():
    from jepsen_tpu.obs import search_report as sr
    recs = [
        {"key": "hot", "engine": "sharded", "events": 10,
         "frontier-peak": 64, "load-factor-peak": 0.5,
         "per-device": {"load-factor-peak":
                        [0.8, 0.1, 0.1, 0.1, 0.1, 0.1, 0.1, 0.1]}},
        {"key": "flat", "engine": "sharded", "events": 10,
         "frontier-peak": 64, "load-factor-peak": 0.2,
         "per-device": {"load-factor-peak": [0.2] * 8}},
        {"key": "solo", "engine": "sparse", "events": 4,
         "frontier-peak": 8, "load-factor-peak": 0.1},
    ]
    assert sr.device_skew(recs[0]) == round(0.8 / (1.5 / 8), 4)
    assert sr.device_skew(recs[1]) == 1.0
    assert sr.device_skew(recs[2]) is None
    text = sr.render_search_report(recs)
    assert "dev-skew" in text
    assert "per-device skew" in text
    # the hot key ranks first in the skew table
    skew_section = text.split("per-device skew")[1]
    assert skew_section.index("hot") < skew_section.index("flat")


# --------------------------------------------------- slow wall-clock


@pytest.mark.slow
def test_forced_skew_wall_clock_win():
    """THE acceptance pin (ISSUE 15): on the recorded forced-skew
    8-fake-device shape, stealing beats the static placement by
    >= 1.2x wall-clock with bit-identical verdicts (steal_ab asserts
    the parity itself). Slow tier: ~60-90s of deliberate sparse CPU
    searches — the parity/scheduler behavior is pinned fast above;
    this guards the WIN against scheduler regressions."""
    model, hs = elastic.forced_skew_histories()
    pre = [enc_mod.encode(model, h) for h in hs]
    ab = elastic.steal_ab(model, pre, _mesh())
    assert ab["verdicts_identical"]
    assert ab["steal_speedup"] >= 1.2, ab
    b_steal = ab["steal"][0]
    b_static = ab["static"][0]
    assert b_steal["steals"] > 0
    # the mesh really was idling under the static placement and the
    # stealer measurably narrowed it
    assert b_steal["busy_frac"] > b_static["busy_frac"]


@pytest.mark.slow
def test_reshard_2d_promotion_parity():
    """The 1-D -> 2-D promotion rung: on a 4x2 mesh the elastic
    ladder crosses from a flat slice onto recruited slices through
    _check_sharded_resume2d with verdicts identical to the static 2-D
    search. Slow tier: the hierarchical shard_map programs are
    multi-minute CPU compiles (the 2-D precedent in test_sharded)."""
    h = adversarial_register_history(n_ops=60, k_crashed=6, seed=7)
    e = enc_mod.encode(CASRegister(), h)
    devs = np.array(jax.devices()).reshape(4, 2)
    mesh2d = Mesh(devs, ("a", "b"))
    r_static = sharded.check_encoded_sharded(e, mesh2d, capacity=128,
                                             max_capacity=1 << 16)
    r_el = sharded.check_encoded_sharded_elastic(
        e, mesh2d, capacity=128, max_capacity=1 << 16)
    keys = ("valid?", "op", "fail-event", "max-frontier")
    assert {k: r_static.get(k) for k in keys} \
        == {k: r_el.get(k) for k in keys}
    # the trail crossed into the 2-D rungs (devices past one slice row)
    assert any(ev["devices"][1] > 2 for ev in
               r_el["reshard"]["events"])
