"""Linearizability: known fixtures + differential testing of the host
engines (wgl vs linear frontier). The TPU engine is differentially tested
against both in test_engine.py. Fixture histories follow the classic
knossos examples."""

import pytest

from jepsen_tpu.checker import linear, wgl
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.history import History, invoke_op, ok_op, fail_op, info_op
from jepsen_tpu.models import CASRegister, Register


def _h(*ops):
    return History.wrap(ops).index()


ENGINES = [wgl.analysis, linear.analysis]


@pytest.mark.parametrize("analysis", ENGINES)
def test_empty(analysis):
    assert analysis(Register(), _h())["valid?"] is True


@pytest.mark.parametrize("analysis", ENGINES)
def test_sequential_valid(analysis):
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(0, "read", None),
        ok_op(0, "read", 1),
    )
    assert analysis(Register(), h)["valid?"] is True


@pytest.mark.parametrize("analysis", ENGINES)
def test_sequential_invalid(analysis):
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(0, "read", None),
        ok_op(0, "read", 2),
    )
    r = analysis(Register(), h)
    assert r["valid?"] is False
    assert r["op"] is not None


@pytest.mark.parametrize("analysis", ENGINES)
def test_concurrent_reorder_valid(analysis):
    # read of 2 is concurrent with write(2): valid only via reordering
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(1, "write", 2),
        invoke_op(2, "read", None),
        ok_op(2, "read", 2),
        ok_op(1, "write", 2),
    )
    assert analysis(Register(), h)["valid?"] is True


@pytest.mark.parametrize("analysis", ENGINES)
def test_stale_read_invalid(analysis):
    # w1 completes, then w2 completes, then a read of 1 begins: stale
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(0, "write", 2),
        ok_op(0, "write", 2),
        invoke_op(1, "read", None),
        ok_op(1, "read", 1),
    )
    assert analysis(Register(), h)["valid?"] is False


@pytest.mark.parametrize("analysis", ENGINES)
def test_crashed_write_may_apply(analysis):
    # crashed write(2); later read sees 2: valid (it may have applied)
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(1, "write", 2),
        info_op(1, "write", 2),
        invoke_op(2, "read", None),
        ok_op(2, "read", 2),
    )
    assert analysis(Register(), h)["valid?"] is True


@pytest.mark.parametrize("analysis", ENGINES)
def test_crashed_write_may_not_apply(analysis):
    # crashed write(2); later read sees 1: also valid
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(1, "write", 2),
        info_op(1, "write", 2),
        invoke_op(2, "read", None),
        ok_op(2, "read", 1),
    )
    assert analysis(Register(), h)["valid?"] is True


@pytest.mark.parametrize("analysis", ENGINES)
def test_crashed_op_stays_concurrent_forever(analysis):
    # crashed write(2) applies *after* an intervening write(3):
    # crashed ops remain concurrent with everything after them
    h = _h(
        invoke_op(0, "write", 2),
        info_op(0, "write", 2),
        invoke_op(1, "write", 3),
        ok_op(1, "write", 3),
        invoke_op(2, "read", None),
        ok_op(2, "read", 3),
        invoke_op(2, "read", None),
        ok_op(2, "read", 2),
    )
    assert analysis(Register(), h)["valid?"] is True


@pytest.mark.parametrize("analysis", ENGINES)
def test_failed_op_never_applies(analysis):
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(1, "write", 2),
        fail_op(1, "write", 2),
        invoke_op(2, "read", None),
        ok_op(2, "read", 2),
    )
    assert analysis(Register(), h)["valid?"] is False


@pytest.mark.parametrize("analysis", ENGINES)
def test_cas_register(analysis):
    h = _h(
        invoke_op(0, "write", 0),
        ok_op(0, "write", 0),
        invoke_op(1, "cas", [0, 1]),
        ok_op(1, "cas", [0, 1]),
        invoke_op(2, "cas", [1, 2]),
        ok_op(2, "cas", [1, 2]),
        invoke_op(0, "read", None),
        ok_op(0, "read", 2),
    )
    assert analysis(CASRegister(), h)["valid?"] is True

    bad = _h(
        invoke_op(0, "write", 0),
        ok_op(0, "write", 0),
        invoke_op(1, "cas", [5, 1]),
        ok_op(1, "cas", [5, 1]),
    )
    assert analysis(CASRegister(), bad)["valid?"] is False


@pytest.mark.parametrize("analysis", ENGINES)
def test_concurrent_cas_both_orders(analysis):
    # two concurrent CASes where only one order linearizes
    h = _h(
        invoke_op(0, "write", 0),
        ok_op(0, "write", 0),
        invoke_op(1, "cas", [0, 1]),
        invoke_op(2, "cas", [1, 2]),
        ok_op(1, "cas", [0, 1]),
        ok_op(2, "cas", [1, 2]),
        invoke_op(0, "read", None),
        ok_op(0, "read", 2),
    )
    assert analysis(CASRegister(), h)["valid?"] is True


def test_differential_wgl_vs_linear_random():
    """The two host engines must agree on random histories, valid and
    corrupted (SURVEY.md §4.8: differential testing is the oracle
    strategy for checker work)."""
    for seed in range(25):
        h = rand_register_history(
            n_ops=40, n_processes=4, n_values=3,
            crash_p=0.08, fail_p=0.08, seed=seed,
        )
        r1 = wgl.analysis(CASRegister(), h)
        r2 = linear.analysis(CASRegister(), h)
        assert r1["valid?"] is True, f"seed {seed}: construction is valid, wgl says {r1}"
        assert r2["valid?"] is True, f"seed {seed}: construction is valid, linear says {r2}"

        bad = corrupt_history(h, seed=seed, n_corruptions=2)
        b1 = wgl.analysis(CASRegister(), bad)
        b2 = linear.analysis(CASRegister(), bad)
        assert b1["valid?"] == b2["valid?"], \
            f"seed {seed}: wgl={b1['valid?']} linear={b2['valid?']}"


def test_linearizable_dispatcher():
    from jepsen_tpu.checker import linearizable
    h = _h(
        invoke_op(0, "write", 1),
        ok_op(0, "write", 1),
        invoke_op(0, "read", None),
        ok_op(0, "read", 1),
    )
    r = linearizable(Register(), algorithm="wgl").check({}, h)
    assert r["valid?"] is True
    assert r["analyzer"] == "wgl"


@pytest.mark.parametrize("analysis", ENGINES)
def test_crashed_acquire_not_pruned(analysis):
    # a crashed acquire (value=None) mutates state and must NOT be pruned:
    # this history is only valid if the crashed acquire took effect
    from jepsen_tpu.models import Mutex
    h = _h(
        invoke_op(0, "acquire", None),
        info_op(0, "acquire", None),
        invoke_op(1, "release", None),
        ok_op(1, "release", None),
    )
    assert analysis(Mutex(), h)["valid?"] is True
