"""Elle-equivalent cycle checker tests: graph machinery, list-append,
rw-register, and the workload wrappers. Fixture style follows the
reference's checker tests (hand-written histories asserted against exact
anomaly classifications)."""

from __future__ import annotations

import pytest

from jepsen_tpu import elle
from jepsen_tpu.elle import Graph, RW, WR, WW, list_append, rw_register
from jepsen_tpu.elle import txn as txn_mod
from jepsen_tpu.generator import fixed_rand
from jepsen_tpu.history import History, Op
from jepsen_tpu.workloads import cycle as cycle_wl


def H(ops):
    h = History()
    for i, o in enumerate(ops):
        op = Op(o)
        op["index"] = i
        h.append(op)
    return h


def txn_pair(process, mops_invoke, mops_ok, final="ok"):
    return [
        {"type": "invoke", "process": process, "f": "txn",
         "value": mops_invoke},
        {"type": final, "process": process, "f": "txn", "value": mops_ok},
    ]


# ----------------------------------------------------------- Graph/SCC


class TestSCC:
    def g3cycle(self):
        g = Graph()
        g.add(0, 1, WW)
        g.add(1, 2, WW)
        g.add(2, 0, WW)
        g.add(2, 3, WW)  # dangling tail, not in the SCC
        return g

    def test_tarjan(self):
        sccs = elle.tarjan_sccs(self.g3cycle())
        assert sorted(map(sorted, sccs)) == [[0, 1, 2]]

    def test_device_matches_tarjan(self):
        sccs = elle.device_sccs(self.g3cycle())
        assert sorted(map(sorted, sccs)) == [[0, 1, 2]]

    def test_device_random_graphs_match(self):
        import random

        r = random.Random(7)
        for _ in range(5):
            g = Graph()
            n = 30
            for _e in range(60):
                g.add(r.randrange(n), r.randrange(n), WW)
            a = sorted(map(sorted, elle.tarjan_sccs(g)))
            b = sorted(map(sorted, elle.device_sccs(g)))
            assert a == b

    def test_g_single_search(self):
        g = Graph()
        g.add(0, 1, RW)
        g.add(1, 0, WW)
        cyc = elle.find_cycle_with_one(g, [0, 1], RW, {WW, WR})
        assert cyc is not None and cyc[0] == cyc[-1]

    def test_cycle_classification_priority(self):
        g = Graph()
        g.add(0, 1, WW)
        g.add(1, 0, WW)
        found = elle.cycle_anomalies(g, by_id={0: {}, 1: {}})
        assert list(found) == ["G0"]


# --------------------------------------------------------- list-append


class TestListAppend:
    def test_valid_history(self):
        h = H([*txn_pair(0, [["append", "x", 1], ["r", "x", None]],
                         [["append", "x", 1], ["r", "x", [1]]]),
               *txn_pair(1, [["append", "x", 2], ["r", "x", None]],
                         [["append", "x", 2], ["r", "x", [1, 2]]])])
        r = list_append.check(None, h)
        assert r["valid?"] is True

    def test_g0_write_cycle(self):
        # T0: x<-1 then y<-2;  T1: y<-1 then x<-2 — ww cycle
        h = H([*txn_pair(0, [["append", "x", 1], ["append", "y", 2]],
                         [["append", "x", 1], ["append", "y", 2]]),
               *txn_pair(1, [["append", "y", 1], ["append", "x", 2]],
                         [["append", "y", 1], ["append", "x", 2]]),
               *txn_pair(2, [["r", "x", None], ["r", "y", None]],
                         [["r", "x", [1, 2]], ["r", "y", [1, 2]]])])
        r = list_append.check({"anomalies": ["G0"]}, h)
        assert r["valid?"] is False
        assert "G0" in r["anomaly-types"]

    def test_g1a_aborted_read(self):
        h = H([*txn_pair(0, [["append", "x", 1]], [["append", "x", 1]],
                         final="fail"),
               *txn_pair(1, [["r", "x", None]], [["r", "x", [1]]])])
        r = list_append.check({"anomalies": ["G1"]}, h)
        assert r["valid?"] is False
        assert "G1a" in r["anomaly-types"]

    def test_g1b_intermediate_read(self):
        h = H([*txn_pair(0, [["append", "x", 1], ["append", "x", 2]],
                         [["append", "x", 1], ["append", "x", 2]]),
               *txn_pair(1, [["r", "x", None]], [["r", "x", [1]]])])
        r = list_append.check({"anomalies": ["G1"]}, h)
        assert r["valid?"] is False
        assert "G1b" in r["anomaly-types"]

    def test_g_single(self):
        # T1 reads x=[] then T2 appends x<-1 and reads y=[]; T1 appends y<-1.
        # T1 -rw-> T2 (T2 overwrote T1's read of x),
        # T2 -rw-> T1 (T1 overwrote T2's read of y): cycle w/ rw edges.
        h = H([*txn_pair(0, [["r", "x", None], ["append", "y", 1]],
                         [["r", "x", []], ["append", "y", 1]]),
               *txn_pair(1, [["append", "x", 1], ["r", "y", None]],
                         [["append", "x", 1], ["r", "y", []]]),
               *txn_pair(2, [["r", "x", None], ["r", "y", None]],
                         [["r", "x", [1]], ["r", "y", [1]]])])
        r = list_append.check({"anomalies": ["G2"]}, h)
        assert r["valid?"] is False
        assert any(a in r["anomaly-types"] for a in ("G-single", "G2"))

    def test_internal(self):
        h = H([*txn_pair(0, [["append", "x", 1], ["r", "x", None]],
                         [["append", "x", 1], ["r", "x", [5, 9]]])])
        r = list_append.check(None, h)
        assert r["valid?"] is False
        assert "internal" in r["anomaly-types"]

    def test_incompatible_order(self):
        h = H([*txn_pair(0, [["r", "x", None]], [["r", "x", [1, 2]]]),
               *txn_pair(1, [["r", "x", None]], [["r", "x", [2, 1]]])])
        r = list_append.check(None, h)
        assert r["valid?"] is False
        assert "incompatible-order" in r["anomaly-types"]

    def test_cycle_has_explanation_steps(self):
        h = H([*txn_pair(0, [["append", "x", 1], ["append", "y", 2]],
                         [["append", "x", 1], ["append", "y", 2]]),
               *txn_pair(1, [["append", "y", 1], ["append", "x", 2]],
                         [["append", "y", 1], ["append", "x", 2]]),
               *txn_pair(2, [["r", "x", None], ["r", "y", None]],
                         [["r", "x", [1, 2]], ["r", "y", [1, 2]]])])
        r = list_append.check({"anomalies": ["G0"]}, h)
        case = r["anomalies"]["G0"][0]
        assert len(case["steps"]) == len(case["cycle"]) - 1
        assert "--[ww]-->" in case["steps"][0]


# --------------------------------------------------------- rw-register


class TestRwRegister:
    def test_valid(self):
        h = H([*txn_pair(0, [["w", "x", 1]], [["w", "x", 1]]),
               *txn_pair(1, [["r", "x", None]], [["r", "x", 1]])])
        r = rw_register.check(None, h)
        assert r["valid?"] is True

    def test_g1a(self):
        h = H([*txn_pair(0, [["w", "x", 1]], [["w", "x", 1]], final="fail"),
               *txn_pair(1, [["r", "x", None]], [["r", "x", 1]])])
        r = rw_register.check(None, h)
        assert r["valid?"] is False
        assert "G1a" in r["anomaly-types"]

    def test_g1b(self):
        h = H([*txn_pair(0, [["w", "x", 1], ["w", "x", 2]],
                         [["w", "x", 1], ["w", "x", 2]]),
               *txn_pair(1, [["r", "x", None]], [["r", "x", 1]])])
        r = rw_register.check(None, h)
        assert r["valid?"] is False
        assert "G1b" in r["anomaly-types"]

    def test_internal(self):
        h = H([*txn_pair(0, [["w", "x", 1], ["r", "x", None]],
                         [["w", "x", 1], ["r", "x", 2]])])
        r = rw_register.check(None, h)
        assert r["valid?"] is False
        assert "internal" in r["anomaly-types"]

    def test_g1c_with_wfr(self):
        # T0 writes x=1, reads y=1; T1 writes y=1, reads x=1:
        # wr cycle (circular information flow)
        h = H([*txn_pair(0, [["w", "x", 1], ["r", "y", None]],
                         [["w", "x", 1], ["r", "y", 1]]),
               *txn_pair(1, [["w", "y", 1], ["r", "x", None]],
                         [["w", "y", 1], ["r", "x", 1]])])
        r = rw_register.check({"anomalies": ["G1"]}, h)
        assert r["valid?"] is False
        assert "G1c" in r["anomaly-types"]

    def test_linearizable_keys_ww(self):
        # sequential non-overlapping writes 1 then 2; a txn that read 1
        # *after* 2 was written has an rw edge forward and a wr edge back:
        # stale read -> G-single under linearizable-keys
        h = H([
            {"type": "invoke", "process": 0, "f": "txn",
             "value": [["w", "x", 1]]},
            {"type": "ok", "process": 0, "f": "txn",
             "value": [["w", "x", 1]]},
            {"type": "invoke", "process": 1, "f": "txn",
             "value": [["w", "x", 2]]},
            {"type": "ok", "process": 1, "f": "txn",
             "value": [["w", "x", 2]]},
            {"type": "invoke", "process": 2, "f": "txn",
             "value": [["r", "x", None]]},
            {"type": "ok", "process": 2, "f": "txn",
             "value": [["r", "x", 1]]},
        ])
        r = rw_register.check({"linearizable-keys": True,
                               "additional-graphs": ["realtime"]}, h)
        assert r["valid?"] is False


# ----------------------------------------------------------- generators


class TestTxnGen:
    def test_append_txns_shape(self):
        with fixed_rand(7):
            stream = txn_mod.append_txns({"key-count": 3,
                                          "min-txn-length": 1,
                                          "max-txn-length": 4})
            txns = [next(stream) for _ in range(50)]
        for t in txns:
            assert 1 <= len(t) <= 4
            for f, k, v in t:
                assert f in ("r", "append")
                assert (v is None) == (f == "r")

    def test_max_writes_per_key_rotates_keys(self):
        with fixed_rand(3):
            stream = txn_mod.wr_txns({"key-count": 2,
                                      "max-writes-per-key": 4})
            writes = {}
            for _ in range(200):
                for f, k, v in next(stream):
                    if f == "w":
                        writes.setdefault(k, []).append(v)
        assert len(writes) > 2  # keys rotated
        for vs in writes.values():
            assert len(vs) <= 4
            assert vs == sorted(vs)  # fresh increasing values per key

    def test_workload_generator_emits_txn_ops(self):
        wl = cycle_wl.append({"key-count": 2})
        with fixed_rand(1):
            op = wl["generator"]()
        assert op["f"] == "txn"
        assert isinstance(op["value"], list)


# --------------------------------------------------- end-to-end wrapper


class TestWorkloadCheckers:
    def test_append_checker_via_protocol(self):
        h = H([*txn_pair(0, [["append", "x", 1]], [["append", "x", 1]]),
               *txn_pair(1, [["r", "x", None]], [["r", "x", [1]]])])
        r = cycle_wl.append().get("checker").check({}, h)
        assert r["valid?"] is True

    def test_wr_checker_via_protocol(self):
        h = H([*txn_pair(0, [["w", "x", 1]], [["w", "x", 1]])])
        r = cycle_wl.wr().get("checker").check({}, h)
        assert r["valid?"] is True

    def test_generic_cycle_checker(self):
        def analyzer(history):
            g = Graph()
            g.add(0, 1, WW)
            g.add(1, 0, WW)
            return g, None, {0: {}, 1: {}}

        r = cycle_wl.checker(analyzer).check({}, H([]))
        assert r["valid?"] is False
        assert "G0" in r["anomaly-types"]
