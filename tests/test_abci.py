"""Protocol-level tests of the tendermint v0.34 ABCI socket protocol
spoken by the native merkleeyes (--proto abci, the default).

Mirrors the reference's in-process lifecycle test
(merkleeyes/app_test.go:20-90: Info → InitChain → CheckTx → BeginBlock →
DeliverTx for every tx type → EndBlock → Commit) but over the real
wire — uvarint-framed protobuf Request/Response — plus golden byte
checks pinning our hand-rolled encoder to the protobuf wire format, and
a cross-protocol equivalence check (same txs through abci and the
legacy custom protocol yield identical app hashes)."""

import shutil

import pytest

from jepsen_tpu.tendermint import abci
from jepsen_tpu.tendermint import gowire as w
from jepsen_tpu.tendermint import merkleeyes as me


def _toolchain():
    return shutil.which("g++") or shutil.which("c++")


pytestmark = pytest.mark.skipif(not _toolchain(),
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("abci")
    with me.LocalServer(sock_path=str(d / "me.sock"),
                        wal_path=str(d / "me.wal"), proto="abci") as srv:
        yield srv


# ------------------------------------------------- golden wire bytes


def test_request_echo_golden_bytes():
    """Request{echo:{message:"hello"}} per proto3: oneof arm echo is
    field 1 (tag 0x0a), RequestEcho.message is field 1 (tag 0x0a)."""
    body = abci.msg_field(abci.REQ_ECHO, abci.str_field(1, "hello"))
    assert body == bytes([0x0A, 0x07, 0x0A, 0x05]) + b"hello"


def test_request_deliver_tx_golden_bytes():
    """Request{deliver_tx:{tx:<3 bytes>}}: arm 9 -> tag 0x4a,
    RequestDeliverTx.tx field 1 -> tag 0x0a."""
    body = abci.msg_field(abci.REQ_DELIVER_TX, abci.bytes_field(1, b"abc"))
    assert body == bytes([0x4A, 0x05, 0x0A, 0x03]) + b"abc"


def test_request_query_golden_bytes():
    """Request{query:{data:"k", path:"/key"}}: arm 6 -> 0x32; data
    field 1, path field 2 -> 0x12."""
    body = abci.msg_field(
        abci.REQ_QUERY, abci.bytes_field(1, b"k") + abci.str_field(2, "/key"))
    assert body == bytes([0x32, 0x09, 0x0A, 0x01]) + b"k" \
        + bytes([0x12, 0x04]) + b"/key"


def test_varint_field_two_byte_value():
    # 300 = 0b10_0101100 -> 0xAC 0x02
    assert abci.varint_field(2, 300) == bytes([0x10, 0xAC, 0x02])
    assert abci.varint_field(2, 0) == b""  # proto3 zero omission


def test_validator_update_roundtrip():
    pk = bytes(range(32))
    vu = abci.validator_update(pk, 5)
    # pub_key:1{ed25519:1 pk} power:2
    assert vu[:2] == bytes([0x0A, 0x22])          # PublicKey msg, 34 bytes
    assert vu[2:4] == bytes([0x0A, 0x20])         # ed25519, 32 bytes
    assert vu[4:36] == pk
    assert vu[36:] == bytes([0x10, 0x05])         # power varint 5
    assert abci.parse_validator_update(vu) == (pk, 5)


# ------------------------------------------------- lifecycle over wire


def test_echo_flush_info(server):
    with server.client() as cl:
        assert cl.echo(b"hello-abci") == b"hello-abci"
        cl.flush()
        height, apphash = cl.info()
        assert height >= 0
        assert len(apphash) == 32


def test_full_block_lifecycle(server):
    """The app_test.go:20-90 sequence over the socket."""
    with server.client() as cl:
        h0, _ = cl.info()

        # InitChain with one genesis validator
        pk = bytes(range(32))
        cl.init_chain([(pk, 10)])

        # CheckTx: too-short tx rejected, well-formed accepted
        assert cl.check_tx(b"short").code == me.CODE_ENCODING_ERROR
        tx = w.set_tx("abci-key", "abci-val")
        assert cl.check_tx(tx).ok

        # One block: every tx type
        cl.begin_block()
        assert cl.deliver_tx(tx).ok
        assert cl.deliver_tx(w.get_tx("abci-key")).data == b"abci-val"
        assert cl.deliver_tx(w.cas_tx("abci-key", "abci-val", "v2")).ok
        bad = cl.deliver_tx(w.cas_tx("abci-key", "abci-val", "v3"))
        assert bad.code == me.CODE_UNAUTHORIZED
        assert cl.deliver_tx(w.rm_tx("abci-key")).ok
        pk2 = bytes(range(32, 64))
        assert cl.deliver_tx(w.valset_change_tx(pk2, 7)).ok
        vs = cl.deliver_tx(w.valset_read_tx())
        assert vs.ok and b"validators" in vs.data
        updates = cl.end_block()
        assert (pk2, 7) in updates
        apphash = cl.commit()
        assert len(apphash) == 32

        # Info reflects the commit
        h1, apphash2 = cl.info()
        assert h1 == h0 + 1
        assert apphash2 == apphash


def test_queries_over_wire(server):
    with server.client() as cl:
        assert cl.tx_commit(w.set_tx("qk", "qv")).ok
        q = cl.query("/key", b"qk")
        assert q.ok and q.value == b"qv" and q.key == b"qk"
        assert q.height > 0
        # /store is an alias
        assert cl.query("/store", b"qk").value == b"qv"
        # /index round-trip: look up the key's index, then fetch by it
        # (like the reference, /index returns the raw tree key — with
        # its "/key/" prefix — app.go:185-197)
        by_idx = cl.query("/index", w.varint(q.index))
        assert by_idx.ok and by_idx.key == b"/key/qk"
        # /size returns a zigzag varint
        size = cl.query("/size", b"")
        n, _ = w.read_varint(size.value, 0)
        assert n >= 1
        # missing key
        missing = cl.query("/key", b"nope-missing")
        assert missing.code == me.CODE_BASE_UNKNOWN_ADDRESS
        # unknown path
        assert cl.query("/bogus", b"").code == me.CODE_UNKNOWN_REQUEST


def test_bad_nonce_over_wire(server):
    with server.client() as cl:
        tx = w.set_tx("nk", "nv")
        assert cl.tx_commit(tx).ok
        r = cl.tx_commit(tx)  # same nonce
        assert r.code == me.CODE_BAD_NONCE


def test_unknown_arm_returns_exception(server):
    with server.client() as cl:
        with pytest.raises(abci.AbciError):
            cl.roundtrip(99, b"", abci.RESP_ECHO)


def test_snapshot_arms_get_empty_responses(server):
    """tendermint probes snapshot support; the app answers with the
    BaseApplication empty responses rather than dying."""
    with server.client() as cl:
        assert cl.roundtrip(12, b"", 13) == {}   # list_snapshots
        assert cl.roundtrip(13, b"", 14) == {}   # offer_snapshot


def test_wal_persists_genesis_validators(tmp_path):
    """InitChain's validator set must survive a crash-restart — on a
    real cluster tendermint only sends InitChain once (height 0), so a
    restarted app would otherwise lose every genesis validator."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    pk = bytes(range(32))
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            cl.init_chain([(pk, 10)])
            vs = cl.tx_commit(w.valset_read_tx())
            assert pk.hex().upper().encode() in vs.data.upper()
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            vs = cl.tx_commit(w.valset_read_tx())
            assert pk.hex().upper().encode() in vs.data.upper()
            # removing the genesis validator works post-restart
            assert cl.tx_commit(w.valset_change_tx(pk, 0)).ok


def test_wal_replays_valset_version(tmp_path):
    """A ValSetCAS that succeeded pre-crash must succeed on replay:
    replay applies EndBlock's version bump per block frame."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    pk1, pk2 = bytes(range(32)), bytes(range(32, 64))
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            assert cl.tx_commit(w.valset_change_tx(pk1, 3)).ok  # version 1
            assert cl.tx_commit(w.valset_cas_tx(1, pk2, 5)).ok  # version 2
            vs1 = cl.tx_commit(w.valset_read_tx()).data
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            vs2 = cl.tx_commit(w.valset_read_tx()).data
            # same validators and same version — the replayed ValSetCAS
            # was accepted, and a CAS against the live version works
            assert sorted(vs1) == sorted(vs2)
            assert b'"version":2' in vs1 and b'"version":2' in vs2
            assert cl.tx_commit(w.valset_cas_tx(2, pk1, 7)).ok


def test_cross_protocol_state_equivalence(tmp_path):
    """The same tx sequence through the ABCI wire and through the legacy
    custom protocol produces identical app hashes — the protocols are
    views of one state machine."""
    txs = [w.set_tx("a", "1", nonce_=bytes(range(12))),
           w.set_tx("b", "2", nonce_=bytes(range(1, 13))),
           w.cas_tx("a", "1", "3", nonce_=bytes(range(2, 14)))]
    hashes = {}
    for proto in ("abci", "custom"):
        with me.LocalServer(sock_path=str(tmp_path / f"{proto}.sock"),
                            proto=proto) as srv:
            with srv.client() as cl:
                for t in txs:
                    assert cl.tx_commit(t).ok
                hashes[proto] = cl.info()[1]
    assert hashes["abci"] == hashes["custom"]
