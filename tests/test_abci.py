"""Protocol-level tests of the tendermint v0.34 ABCI socket protocol
spoken by the native merkleeyes (--proto abci, the default).

Mirrors the reference's in-process lifecycle test
(merkleeyes/app_test.go:20-90: Info → InitChain → CheckTx → BeginBlock →
DeliverTx for every tx type → EndBlock → Commit) but over the real
wire — uvarint-framed protobuf Request/Response — plus golden byte
checks pinning our hand-rolled encoder to the protobuf wire format, and
a cross-protocol equivalence check (same txs through abci and the
legacy custom protocol yield identical app hashes)."""

import shutil

import pytest

from jepsen_tpu.tendermint import abci
from jepsen_tpu.tendermint import gowire as w
from jepsen_tpu.tendermint import merkleeyes as me


def _toolchain():
    return shutil.which("g++") or shutil.which("c++")


pytestmark = pytest.mark.skipif(not _toolchain(),
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("abci")
    with me.LocalServer(sock_path=str(d / "me.sock"),
                        wal_path=str(d / "me.wal"), proto="abci") as srv:
        yield srv


# ------------------------------------------------- golden wire bytes


def test_request_echo_golden_bytes():
    """Request{echo:{message:"hello"}} per proto3: oneof arm echo is
    field 1 (tag 0x0a), RequestEcho.message is field 1 (tag 0x0a)."""
    body = abci.msg_field(abci.REQ_ECHO, abci.str_field(1, "hello"))
    assert body == bytes([0x0A, 0x07, 0x0A, 0x05]) + b"hello"


def test_request_deliver_tx_golden_bytes():
    """Request{deliver_tx:{tx:<3 bytes>}}: arm 9 -> tag 0x4a,
    RequestDeliverTx.tx field 1 -> tag 0x0a."""
    body = abci.msg_field(abci.REQ_DELIVER_TX, abci.bytes_field(1, b"abc"))
    assert body == bytes([0x4A, 0x05, 0x0A, 0x03]) + b"abc"


def test_request_query_golden_bytes():
    """Request{query:{data:"k", path:"/key"}}: arm 6 -> 0x32; data
    field 1, path field 2 -> 0x12."""
    body = abci.msg_field(
        abci.REQ_QUERY, abci.bytes_field(1, b"k") + abci.str_field(2, "/key"))
    assert body == bytes([0x32, 0x09, 0x0A, 0x01]) + b"k" \
        + bytes([0x12, 0x04]) + b"/key"


def test_varint_field_two_byte_value():
    # 300 = 0b10_0101100 -> 0xAC 0x02
    assert abci.varint_field(2, 300) == bytes([0x10, 0xAC, 0x02])
    assert abci.varint_field(2, 0) == b""  # proto3 zero omission


def test_validator_update_roundtrip():
    pk = bytes(range(32))
    vu = abci.validator_update(pk, 5)
    # pub_key:1{ed25519:1 pk} power:2
    assert vu[:2] == bytes([0x0A, 0x22])          # PublicKey msg, 34 bytes
    assert vu[2:4] == bytes([0x0A, 0x20])         # ed25519, 32 bytes
    assert vu[4:36] == pk
    assert vu[36:] == bytes([0x10, 0x05])         # power varint 5
    assert abci.parse_validator_update(vu) == (pk, 5)


# ------------------------------------------------- lifecycle over wire


def test_echo_flush_info(server):
    with server.client() as cl:
        assert cl.echo(b"hello-abci") == b"hello-abci"
        cl.flush()
        height, apphash = cl.info()
        assert height >= 0
        assert len(apphash) == 32


def test_full_block_lifecycle(server):
    """The app_test.go:20-90 sequence over the socket."""
    with server.client() as cl:
        h0, _ = cl.info()

        # InitChain with one genesis validator
        pk = bytes(range(32))
        cl.init_chain([(pk, 10)])

        # CheckTx: too-short tx rejected, well-formed accepted
        assert cl.check_tx(b"short").code == me.CODE_ENCODING_ERROR
        tx = w.set_tx("abci-key", "abci-val")
        assert cl.check_tx(tx).ok

        # One block: every tx type
        cl.begin_block()
        assert cl.deliver_tx(tx).ok
        assert cl.deliver_tx(w.get_tx("abci-key")).data == b"abci-val"
        assert cl.deliver_tx(w.cas_tx("abci-key", "abci-val", "v2")).ok
        bad = cl.deliver_tx(w.cas_tx("abci-key", "abci-val", "v3"))
        assert bad.code == me.CODE_UNAUTHORIZED
        assert cl.deliver_tx(w.rm_tx("abci-key")).ok
        pk2 = bytes(range(32, 64))
        assert cl.deliver_tx(w.valset_change_tx(pk2, 7)).ok
        vs = cl.deliver_tx(w.valset_read_tx())
        assert vs.ok and b"validators" in vs.data
        updates = cl.end_block()
        assert (pk2, 7) in updates
        apphash = cl.commit()
        assert len(apphash) == 32

        # Info reflects the commit
        h1, apphash2 = cl.info()
        assert h1 == h0 + 1
        assert apphash2 == apphash


def test_queries_over_wire(server):
    with server.client() as cl:
        assert cl.tx_commit(w.set_tx("qk", "qv")).ok
        q = cl.query("/key", b"qk")
        assert q.ok and q.value == b"qv" and q.key == b"qk"
        assert q.height > 0
        # /store is an alias
        assert cl.query("/store", b"qk").value == b"qv"
        # /index round-trip: look up the key's index, then fetch by it
        # (like the reference, /index returns the raw tree key — with
        # its "/key/" prefix — app.go:185-197)
        by_idx = cl.query("/index", w.varint(q.index))
        assert by_idx.ok and by_idx.key == b"/key/qk"
        # /size returns a zigzag varint
        size = cl.query("/size", b"")
        n, _ = w.read_varint(size.value, 0)
        assert n >= 1
        # missing key
        missing = cl.query("/key", b"nope-missing")
        assert missing.code == me.CODE_BASE_UNKNOWN_ADDRESS
        # unknown path
        assert cl.query("/bogus", b"").code == me.CODE_UNKNOWN_REQUEST


def test_bad_nonce_over_wire(server):
    with server.client() as cl:
        tx = w.set_tx("nk", "nv")
        assert cl.tx_commit(tx).ok
        r = cl.tx_commit(tx)  # same nonce
        assert r.code == me.CODE_BAD_NONCE


def test_unknown_arm_returns_exception(server):
    with server.client() as cl:
        with pytest.raises(abci.AbciError):
            cl.roundtrip(99, b"", abci.RESP_ECHO)


def test_snapshot_arms_get_empty_responses(server):
    """tendermint probes snapshot support; the app answers with the
    BaseApplication empty responses rather than dying."""
    with server.client() as cl:
        assert cl.roundtrip(12, b"", 13) == {}   # list_snapshots
        assert cl.roundtrip(13, b"", 14) == {}   # offer_snapshot


def test_wal_persists_genesis_validators(tmp_path):
    """InitChain's validator set must survive a crash-restart — on a
    real cluster tendermint only sends InitChain once (height 0), so a
    restarted app would otherwise lose every genesis validator."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    pk = bytes(range(32))
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            cl.init_chain([(pk, 10)])
            vs = cl.tx_commit(w.valset_read_tx())
            assert pk.hex().upper().encode() in vs.data.upper()
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            vs = cl.tx_commit(w.valset_read_tx())
            assert pk.hex().upper().encode() in vs.data.upper()
            # removing the genesis validator works post-restart
            assert cl.tx_commit(w.valset_change_tx(pk, 0)).ok


def test_wal_replays_valset_version(tmp_path):
    """A ValSetCAS that succeeded pre-crash must succeed on replay:
    replay applies EndBlock's version bump per block frame."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    pk1, pk2 = bytes(range(32)), bytes(range(32, 64))
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            assert cl.tx_commit(w.valset_change_tx(pk1, 3)).ok  # version 1
            assert cl.tx_commit(w.valset_cas_tx(1, pk2, 5)).ok  # version 2
            vs1 = cl.tx_commit(w.valset_read_tx()).data
    with me.LocalServer(sock_path=sock, wal_path=wal, proto="abci") as srv:
        with srv.client() as cl:
            vs2 = cl.tx_commit(w.valset_read_tx()).data
            # same validators and same version — the replayed ValSetCAS
            # was accepted, and a CAS against the live version works
            assert sorted(vs1) == sorted(vs2)
            assert b'"version":2' in vs1 and b'"version":2' in vs2
            assert cl.tx_commit(w.valset_cas_tx(2, pk1, 7)).ok


# ------------------------------------- conformance transcript fixture
#
# Every request byte below is hand-derived from the tendermint v0.34
# proto spec (abci/types/types.proto oneof arms) and the reference's
# tx parser (merkleeyes/app.go:486-540: uvarint length ∥ bytes — NOT
# the stale README's Len(Len(B))|Len(B)|B scheme; binary.Uvarint is
# authoritative) — deliberately NOT built with
# jepsen_tpu.tendermint.abci/gowire, so the fixture pins the C++
# server against an independent reading of the protocol.


def _uv(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _frame(body: bytes) -> bytes:
    return _uv(len(body)) + body


def _read_frame(sock) -> bytes:
    ln = shift = 0
    while True:
        b = sock.recv(1)
        assert b, "server closed mid-frame"
        ln |= (b[0] & 0x7F) << shift
        if not b[0] & 0x80:
            break
        shift += 7
    out = b""
    while len(out) < ln:
        chunk = sock.recv(ln - len(out))
        assert chunk, "server closed mid-body"
        out += chunk
    return out


def _fields(body: bytes) -> dict:
    """Minimal proto3 scanner: field -> last value (varint int or
    len-delimited bytes). Independent of the repo's pb reader."""
    out = {}
    i = 0
    while i < len(body):
        tag = body[i]
        f, wire = tag >> 3, tag & 7
        i += 1
        if wire == 0:  # varint
            v = shift = 0
            while True:
                v |= (body[i] & 0x7F) << shift
                i += 1
                if not body[i - 1] & 0x80:
                    break
                shift += 7
            out[f] = v
        elif wire == 2:  # len-delimited
            ln = body[i]
            i += 1
            out[f] = body[i:i + ln]
            i += ln
        else:
            raise AssertionError(f"unexpected wire type {wire}")
    return out


def _arm(body: bytes):
    """(oneof arm number, payload) of a Response frame."""
    assert body[0] & 7 == 2, "oneof arm must be len-delimited"
    fields = _fields(body)
    arm = body[0] >> 3
    return arm, fields[arm]


def test_v034_transcript_fixture(tmp_path):
    """Replays a hand-encoded handshake + InitChain(2 validators) +
    full block + Info + prove=true Query transcript against the C++
    server, raw bytes on the unix socket (VERDICT r2 ask #8: a fixture
    independent of this repo's own encoder; reference semantics
    app_test.go:20-90 and app.go:158-217)."""
    import socket

    pk_a, pk_b = bytes(range(32)), bytes(range(64, 96))
    vu_a = bytes([0x0A, 0x22, 0x0A, 0x20]) + pk_a + bytes([0x10, 0x0A])
    vu_b = bytes([0x0A, 0x22, 0x0A, 0x20]) + pk_b + bytes([0x10, 0x07])
    init_body = (bytes([0x12, 0x07]) + b"tm-test"
                 + bytes([0x22, 0x26]) + vu_a
                 + bytes([0x22, 0x26]) + vu_b)
    # NONCE | 01 | uvarint-len "tk" | uvarint-len "tv"
    # (merkleeyes/app.go:521-523 minTxLen, :486-519 unmarshalBytes)
    tx = (bytes.fromhex("00112233445566778899AABB") + bytes([0x01])
          + bytes([0x02]) + b"tk" + bytes([0x02]) + b"tv")
    deliver_body = bytes([0x0A, len(tx)]) + tx
    query_body = (bytes([0x0A, 0x02]) + b"tk"
                  + bytes([0x12, 0x04]) + b"/key"
                  + bytes([0x20, 0x01]))        # prove = true

    transcript = [
        # request frame                                  expected resp arm
        (bytes([0x0A, 0x07, 0x0A, 0x05]) + b"hello",     2),   # echo
        (bytes([0x12, 0x00]),                            3),   # flush
        (bytes([0x1A, 0x00]),                            4),   # info
        (bytes([0x2A, len(init_body)]) + init_body,      6),   # init_chain
        (bytes([0x3A, 0x00]),                            8),   # begin_block
        (bytes([0x4A, len(deliver_body)]) + deliver_body, 10), # deliver_tx
        (bytes([0x52, 0x02, 0x08, 0x01]),                11),  # end_block h=1
        (bytes([0x5A, 0x00]),                            12),  # commit
        (bytes([0x1A, 0x00]),                            4),   # info again
        (bytes([0x32, len(query_body)]) + query_body,    7),   # query+prove
    ]

    sock_path = str(tmp_path / "conf.sock")
    with me.LocalServer(sock_path=sock_path, proto="abci"):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(sock_path)
        try:
            resp = []
            for req, want_arm in transcript:
                s.sendall(_frame(req))
                arm, payload = _arm(_read_frame(s))
                assert arm == want_arm, (arm, want_arm, payload)
                resp.append(_fields(payload) if payload else {})
        finally:
            s.close()

    echo, _, info0, init, _, deliver, endb, commit, info1, query = resp
    assert echo[1] == b"hello"
    # fresh server: height 0 (proto3 omits zero -> field 4 absent)
    assert info0.get(4, 0) == 0
    # InitChain returns the genesis app hash (field 3)
    assert len(init[3]) == 32
    # the Set tx was accepted (code 0 omitted on the wire)
    assert deliver.get(1, 0) == 0
    # EndBlock: no validator updates for a plain Set block
    assert 1 not in endb
    # Commit returns the 32-byte app hash (field 2)
    assert len(commit[2]) == 32
    # Info now reports non-zero height and the committed hash
    assert info1[4] == 1
    assert info1[5] == commit[2]
    # Query with prove=true is rejected (app.go:174-176)
    assert query[1] == me.CODE_INTERNAL
    assert b"proof" in query[3]


def test_cross_protocol_state_equivalence(tmp_path):
    """The same tx sequence through the ABCI wire and through the legacy
    custom protocol produces identical app hashes — the protocols are
    views of one state machine."""
    txs = [w.set_tx("a", "1", nonce_=bytes(range(12))),
           w.set_tx("b", "2", nonce_=bytes(range(1, 13))),
           w.cas_tx("a", "1", "3", nonce_=bytes(range(2, 14)))]
    hashes = {}
    for proto in ("abci", "custom"):
        with me.LocalServer(sock_path=str(tmp_path / f"{proto}.sock"),
                            proto=proto) as srv:
            with srv.client() as cl:
                for t in txs:
                    assert cl.tx_commit(t).ok
                hashes[proto] = cl.info()[1]
    assert hashes["abci"] == hashes["custom"]
