"""Frontier checkpoint/resume for long device searches (the
checkpoint/resume capability beyond the reference's re-analysis path,
SURVEY.md §5.4 / §7)."""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu.histories import rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, engine


def _encoded(seed=3, n_ops=160, crash_p=0.01, valid=True):
    h = rand_register_history(n_ops=n_ops, n_processes=6, n_values=4,
                              crash_p=crash_p, fail_p=0.05, busy=0.7,
                              seed=seed)
    if not valid:
        # corrupt one ok read to a value never written
        for o in reversed(h):
            if o.get("type") == "ok" and o.get("f") == "read" \
                    and o.get("value") is not None:
                o["value"] = 993
                break
    return enc_mod.encode(CASRegister(), h)


def test_resumable_matches_oneshot_valid():
    e = _encoded(seed=5)
    ref = engine.check_encoded(e, capacity=256)
    res = engine.check_encoded_resumable(e, capacity=256,
                                         checkpoint_every=16)
    assert res["valid?"] == ref["valid?"] is True
    assert res["max-frontier"] == ref["max-frontier"]


def test_resumable_matches_oneshot_invalid():
    e = _encoded(seed=6, valid=False)
    ref = engine.check_encoded(e, capacity=256)
    res = engine.check_encoded_resumable(e, capacity=256,
                                         checkpoint_every=16)
    assert ref["valid?"] is False and res["valid?"] is False
    assert res["op"] == ref["op"]
    assert res["fail-event"] == ref["fail-event"]


def test_checkpoint_save_load_resume(tmp_path):
    e = _encoded(seed=7)
    ref = engine.check_encoded(e, capacity=256)

    # run the first chunks only, capturing checkpoints
    cps = []

    class Stop(Exception):
        pass

    def cb(cp):
        cps.append(cp)
        if len(cps) >= 3:
            raise Stop  # simulate preemption mid-search

    with pytest.raises(Stop):
        engine.check_encoded_resumable(e, capacity=256,
                                       checkpoint_every=8,
                                       checkpoint_cb=cb)
    assert cps and cps[-1].event_index < e.n_returns

    # persist, reload, resume to completion
    path = str(tmp_path / "frontier.npz")
    cps[-1].save(path)
    loaded = engine.FrontierCheckpoint.load(path)
    assert loaded.event_index == cps[-1].event_index
    assert (loaded.live == cps[-1].live).all()

    res = engine.check_encoded_resumable(e, checkpoint_every=64,
                                         resume=loaded)
    assert res["valid?"] == ref["valid?"]
    assert res["max-frontier"] >= 1


def test_checkpoint_rejects_wrong_history(tmp_path):
    e1, e2 = _encoded(seed=8), _encoded(seed=9)
    cps = []
    engine.check_encoded_resumable(e1, checkpoint_every=8,
                                   checkpoint_cb=cps.append)
    assert cps
    with pytest.raises(ValueError, match="different history"):
        engine.check_encoded_resumable(e2, resume=cps[0])


def test_overflow_regrows_within_resume():
    # tiny capacity forces overflow doubling; the result must still
    # match the roomy one-shot check
    e = _encoded(seed=10, n_ops=120)
    ref = engine.check_encoded(e, capacity=1024)
    res = engine.check_encoded_resumable(e, capacity=64,
                                         checkpoint_every=16)
    assert res["valid?"] == ref["valid?"]
    assert res["capacity"] >= 64


# ---------------- sharded (mesh) checkpoint/resume -------------------


def _mesh(n=8):
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:n]), ("frontier",))


@pytest.mark.slow
def test_sharded_resumable_matches_oneshot():
    """slow-marked: 4 full sharded searches (ref + resumable, valid +
    invalid) on the 8-way virtual mesh ≈ 40s of mostly shard_map
    compile on the 2-core CI box; unrunnable before the jax-version
    shim, so tier-1 never carried it. The smaller-mesh resume test
    below keeps the save/load/resume path in tier-1."""
    from jepsen_tpu.parallel import sharded

    mesh = _mesh()
    for valid, seed in ((True, 5), (False, 6)):
        e = _encoded(seed=seed, valid=valid)
        ref = sharded.check_encoded_sharded(e, mesh, capacity=64 * 8)
        res = sharded.check_encoded_sharded_resumable(
            e, mesh, capacity=64 * 8, checkpoint_every=16)
        assert res["valid?"] == ref["valid?"] is valid
        if not valid:
            assert res["op"] == ref["op"]
            assert res["fail-event"] == ref["fail-event"]


def test_sharded_checkpoint_resumes_on_smaller_mesh(tmp_path):
    """The elastic-recovery property: a search checkpointed on 8
    devices resumes — via save/load — on a 4-device mesh (restored
    rows re-route to their hash-owners on the CURRENT topology)."""
    from jepsen_tpu.parallel import engine as eng, sharded

    e = _encoded(seed=7)
    ref = sharded.check_encoded_sharded(e, _mesh(8), capacity=64 * 8)

    cps = []

    class Stop(Exception):
        pass

    def cb(cp):
        cps.append(cp)
        if len(cps) >= 2:
            raise Stop  # simulate preemption mid-search

    with pytest.raises(Stop):
        sharded.check_encoded_sharded_resumable(
            e, _mesh(8), capacity=64 * 8, checkpoint_every=8,
            checkpoint_cb=cb)
    assert cps and cps[-1].event_index < e.n_returns

    path = str(tmp_path / "sharded-frontier.npz")
    cps[-1].save(path)
    loaded = eng.FrontierCheckpoint.load(path)

    res = sharded.check_encoded_sharded_resumable(
        e, _mesh(4), checkpoint_every=64, resume=loaded)
    assert res["valid?"] == ref["valid?"] is True
    assert res["devices"] == 4


def test_sharded_checkpoint_rejects_wrong_history():
    from jepsen_tpu.parallel import sharded

    e1, e2 = _encoded(seed=8), _encoded(seed=9)
    cps = []
    sharded.check_encoded_sharded_resumable(
        e1, _mesh(), capacity=64 * 8, checkpoint_every=8,
        checkpoint_cb=cps.append)
    assert cps
    with pytest.raises(ValueError, match="different history"):
        sharded.check_encoded_sharded_resumable(e2, _mesh(),
                                                resume=cps[0])


@pytest.mark.slow
def test_sharded_restore_route_handles_skewed_rows():
    """Restore-route destinations are maximally skewed (each device's
    rows return to that device), so its buckets must be worst-case
    sized: with the frontier peaking ~2.5k at global capacity 4096 on
    8 devices, per-device restore load (~320 rows) exceeds the
    uniform-slack bucket width (2*512/8 = 128) — under the old sizing
    every chunk spuriously overflowed and the capacity inflated; it
    must stay at 4096. (Shape right-sized from k=10/capacity-16384
    when the jax-version shim first made this test runnable: the k=8
    shape pins the same regression at a quarter of the sort work —
    4 minutes of CPU was buying no extra coverage; slow-marked even
    so — one worst-case-bucket regression pin is not worth 50s of
    every tier-1 run.)"""
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.parallel import sharded

    h = adversarial_register_history(n_ops=60, k_crashed=8, seed=4)
    e = enc_mod.encode(CASRegister(), h)
    mesh = _mesh(8)
    ref = sharded.check_encoded_sharded(e, mesh, capacity=4096)
    assert ref["valid?"] is True and ref["capacity"] == 4096, ref
    res = sharded.check_encoded_sharded_resumable(
        e, mesh, capacity=4096, checkpoint_every=8)
    assert res["valid?"] is True, res
    assert res["capacity"] == 4096, \
        f"spurious restore-route overflow inflated capacity: {res}"
