"""Frontier checkpoint/resume for long device searches (the
checkpoint/resume capability beyond the reference's re-analysis path,
SURVEY.md §5.4 / §7)."""

from __future__ import annotations

import numpy as np
import pytest

from jepsen_tpu.histories import rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, engine


def _encoded(seed=3, n_ops=160, crash_p=0.01, valid=True):
    h = rand_register_history(n_ops=n_ops, n_processes=6, n_values=4,
                              crash_p=crash_p, fail_p=0.05, busy=0.7,
                              seed=seed)
    if not valid:
        # corrupt one ok read to a value never written
        for o in reversed(h):
            if o.get("type") == "ok" and o.get("f") == "read" \
                    and o.get("value") is not None:
                o["value"] = 993
                break
    return enc_mod.encode(CASRegister(), h)


def test_resumable_matches_oneshot_valid():
    e = _encoded(seed=5)
    ref = engine.check_encoded(e, capacity=256)
    res = engine.check_encoded_resumable(e, capacity=256,
                                         checkpoint_every=16)
    assert res["valid?"] == ref["valid?"] is True
    assert res["max-frontier"] == ref["max-frontier"]


def test_resumable_matches_oneshot_invalid():
    e = _encoded(seed=6, valid=False)
    ref = engine.check_encoded(e, capacity=256)
    res = engine.check_encoded_resumable(e, capacity=256,
                                         checkpoint_every=16)
    assert ref["valid?"] is False and res["valid?"] is False
    assert res["op"] == ref["op"]
    assert res["fail-event"] == ref["fail-event"]


def test_checkpoint_save_load_resume(tmp_path):
    e = _encoded(seed=7)
    ref = engine.check_encoded(e, capacity=256)

    # run the first chunks only, capturing checkpoints
    cps = []

    class Stop(Exception):
        pass

    def cb(cp):
        cps.append(cp)
        if len(cps) >= 3:
            raise Stop  # simulate preemption mid-search

    with pytest.raises(Stop):
        engine.check_encoded_resumable(e, capacity=256,
                                       checkpoint_every=8,
                                       checkpoint_cb=cb)
    assert cps and cps[-1].event_index < e.n_returns

    # persist, reload, resume to completion
    path = str(tmp_path / "frontier.npz")
    cps[-1].save(path)
    loaded = engine.FrontierCheckpoint.load(path)
    assert loaded.event_index == cps[-1].event_index
    assert (loaded.live == cps[-1].live).all()

    res = engine.check_encoded_resumable(e, checkpoint_every=64,
                                         resume=loaded)
    assert res["valid?"] == ref["valid?"]
    assert res["max-frontier"] >= 1


def test_checkpoint_rejects_wrong_history(tmp_path):
    e1, e2 = _encoded(seed=8), _encoded(seed=9)
    cps = []
    engine.check_encoded_resumable(e1, checkpoint_every=8,
                                   checkpoint_cb=cps.append)
    assert cps
    with pytest.raises(ValueError, match="different history"):
        engine.check_encoded_resumable(e2, resume=cps[0])


def test_overflow_regrows_within_resume():
    # tiny capacity forces overflow doubling; the result must still
    # match the roomy one-shot check
    e = _encoded(seed=10, n_ops=120)
    ref = engine.check_encoded(e, capacity=1024)
    res = engine.check_encoded_resumable(e, capacity=64,
                                         checkpoint_every=16)
    assert res["valid?"] == ref["valid?"]
    assert res["capacity"] >= 64


# ---------------- sharded (mesh) checkpoint/resume -------------------


def _mesh(n=8):
    import jax
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:n]), ("frontier",))


def test_sharded_resumable_matches_oneshot():
    from jepsen_tpu.parallel import sharded

    mesh = _mesh()
    for valid, seed in ((True, 5), (False, 6)):
        e = _encoded(seed=seed, valid=valid)
        ref = sharded.check_encoded_sharded(e, mesh, capacity=64 * 8)
        res = sharded.check_encoded_sharded_resumable(
            e, mesh, capacity=64 * 8, checkpoint_every=16)
        assert res["valid?"] == ref["valid?"] is valid
        if not valid:
            assert res["op"] == ref["op"]
            assert res["fail-event"] == ref["fail-event"]


def test_sharded_checkpoint_resumes_on_smaller_mesh(tmp_path):
    """The elastic-recovery property: a search checkpointed on 8
    devices resumes — via save/load — on a 4-device mesh (restored
    rows re-route to their hash-owners on the CURRENT topology)."""
    from jepsen_tpu.parallel import engine as eng, sharded

    e = _encoded(seed=7)
    ref = sharded.check_encoded_sharded(e, _mesh(8), capacity=64 * 8)

    cps = []

    class Stop(Exception):
        pass

    def cb(cp):
        cps.append(cp)
        if len(cps) >= 2:
            raise Stop  # simulate preemption mid-search

    with pytest.raises(Stop):
        sharded.check_encoded_sharded_resumable(
            e, _mesh(8), capacity=64 * 8, checkpoint_every=8,
            checkpoint_cb=cb)
    assert cps and cps[-1].event_index < e.n_returns

    path = str(tmp_path / "sharded-frontier.npz")
    cps[-1].save(path)
    loaded = eng.FrontierCheckpoint.load(path)

    res = sharded.check_encoded_sharded_resumable(
        e, _mesh(4), checkpoint_every=64, resume=loaded)
    assert res["valid?"] == ref["valid?"] is True
    assert res["devices"] == 4


def test_sharded_checkpoint_rejects_wrong_history():
    from jepsen_tpu.parallel import sharded

    e1, e2 = _encoded(seed=8), _encoded(seed=9)
    cps = []
    sharded.check_encoded_sharded_resumable(
        e1, _mesh(), capacity=64 * 8, checkpoint_every=8,
        checkpoint_cb=cps.append)
    assert cps
    with pytest.raises(ValueError, match="different history"):
        sharded.check_encoded_sharded_resumable(e2, _mesh(),
                                                resume=cps[0])


def test_sharded_restore_route_handles_skewed_rows():
    """Restore-route destinations are maximally skewed (each device's
    rows return to that device), so its buckets must be worst-case
    sized: with frontier ~2^10 at global capacity 2048 on 8 devices,
    per-device restore load (~137 rows) exceeds the uniform-slack
    bucket width (64) — under the old sizing every chunk spuriously
    overflowed and the capacity inflated; it must stay at 2048."""
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.parallel import sharded

    h = adversarial_register_history(n_ops=120, k_crashed=10, seed=4)
    e = enc_mod.encode(CASRegister(), h)
    mesh = _mesh(8)
    ref = sharded.check_encoded_sharded(e, mesh, capacity=16384)
    assert ref["valid?"] is True and ref["capacity"] == 16384, ref
    # peak frontier ~12k -> ~1.5k rows per device at restore, far past
    # the old uniform-slack bucket width (2*2048/8 = 512)
    res = sharded.check_encoded_sharded_resumable(
        e, mesh, capacity=16384, checkpoint_every=8)
    assert res["valid?"] is True, res
    assert res["capacity"] == 16384, \
        f"spurious restore-route overflow inflated capacity: {res}"
