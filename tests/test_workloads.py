"""Workload-suite tests: bank, long-fork, causal, causal-reverse, adya.
History fixtures asserted against exact results, mirroring the
reference's checker tests (test strategy SURVEY.md §4.3)."""

from __future__ import annotations

from jepsen_tpu.generator import fixed_rand
from jepsen_tpu import generator as gen
from jepsen_tpu.generator.testing import simulate
from jepsen_tpu.history import History, Op
from jepsen_tpu.independent import ktuple as kv
from jepsen_tpu.workloads import (adya, bank, causal, causal_reverse,
                                  long_fork)


def H(ops):
    h = History()
    for i, o in enumerate(ops):
        op = Op(o)
        op.setdefault("index", i)
        op.setdefault("time", i)
        h.append(op)
    return h


def ok_read(process, value, **kw):
    return [{"type": "invoke", "process": process, "f": "read",
             "value": None, **kw},
            {"type": "ok", "process": process, "f": "read",
             "value": value, **kw}]


# ------------------------------------------------------------------ bank


class TestBank:
    def test_valid(self):
        t = {"accounts": [0, 1], "total-amount": 10}
        h = H(ok_read(0, {0: 4, 1: 6}) + ok_read(1, {0: 10, 1: 0}))
        r = bank.BankChecker().check(t, h)
        assert r["valid?"] is True
        assert r["read-count"] == 2

    def test_wrong_total(self):
        t = {"accounts": [0, 1], "total-amount": 10}
        h = H(ok_read(0, {0: 4, 1: 7}))
        r = bank.BankChecker().check(t, h)
        assert r["valid?"] is False
        assert r["errors"]["wrong-total"]["count"] == 1
        assert r["first-error"]["total"] == 11

    def test_negative_value(self):
        t = {"accounts": [0, 1], "total-amount": 10}
        h = H(ok_read(0, {0: -2, 1: 12}))
        r = bank.BankChecker().check(t, h)
        assert r["valid?"] is False
        assert "negative-value" in r["errors"]
        r2 = bank.BankChecker({"negative-balances?": True}).check(t, h)
        assert r2["valid?"] is True

    def test_nil_balance_and_unexpected_key(self):
        t = {"accounts": [0, 1], "total-amount": 10}
        r = bank.BankChecker().check(t, H(ok_read(0, {0: 4, 1: None})))
        assert r["valid?"] is False and "nil-balance" in r["errors"]
        r = bank.BankChecker().check(t, H(ok_read(0, {0: 4, 9: 6})))
        assert r["valid?"] is False and "unexpected-key" in r["errors"]

    def test_generator_emits_valid_ops(self):
        wl = bank.workload()
        test = {**wl, "concurrency": 4}
        with fixed_rand(11):
            h = simulate(gen.limit(40, wl["generator"]),
                         lambda c, inv: Op({**inv, "type": "ok"}),
                         test=test)
        invokes = [o for o in h if o.get("type") == "invoke"]
        assert len(invokes) > 10
        for o in invokes:
            assert o["f"] in ("read", "transfer")
            if o["f"] == "transfer":
                v = o["value"]
                assert v["from"] != v["to"]
                assert 1 <= v["amount"] <= 5

    def test_plotter_series(self):
        t = {"accounts": [0, 1], "total-amount": 10, "nodes": ["n1", "n2"]}
        h = H(ok_read(0, {0: 4, 1: 6}) + ok_read(1, {0: 10, 1: 0}))
        r = bank.BalancePlotter().check(t, h)
        assert r["valid?"] is True
        assert set(r["series"]) == {"n1", "n2"}


# ------------------------------------------------------------- long-fork


def lf_read(process, pairs):
    v = [["r", k, val] for k, val in pairs]
    return [{"type": "invoke", "process": process, "f": "read",
             "value": [["r", k, None] for k, _ in pairs]},
            {"type": "ok", "process": process, "f": "read", "value": v}]


def lf_write(process, k):
    v = [["w", k, 1]]
    return [{"type": "invoke", "process": process, "f": "write", "value": v},
            {"type": "ok", "process": process, "f": "write", "value": v}]


class TestLongFork:
    def test_valid(self):
        h = H(lf_write(0, 0) + lf_write(1, 1)
              + lf_read(2, [(0, 1), (1, None)])
              + lf_read(3, [(0, 1), (1, 1)]))
        r = long_fork.LongForkChecker(2).check({}, h)
        assert r["valid?"] is True

    def test_fork(self):
        # r3 sees x=nil y=1; r4 sees x=1 y=nil: incomparable
        h = H(lf_write(0, 0) + lf_write(1, 1)
              + lf_read(2, [(0, None), (1, 1)])
              + lf_read(3, [(0, 1), (1, None)]))
        r = long_fork.LongForkChecker(2).check({}, h)
        assert r["valid?"] is False
        assert len(r["forks"]) == 1

    def test_multiple_writes_unknown(self):
        h = H(lf_write(0, 0) + lf_write(1, 0))
        r = long_fork.LongForkChecker(2).check({}, h)
        assert r["valid?"] == "unknown"
        assert r["error"] == ["multiple-writes", 0]

    def test_distinct_values_illegal(self):
        h = H(lf_read(0, [(0, 1), (1, None)])
              + lf_read(1, [(0, 2), (1, None)]))
        r = long_fork.LongForkChecker(2).check({}, h)
        assert r["valid?"] == "unknown"

    def test_group_math(self):
        assert long_fork.group_for(2, 5) == [4, 5]
        assert long_fork.group_for(3, 3) == [3, 4, 5]

    def test_generator_write_then_group_read(self):
        wl = long_fork.workload(2)
        with fixed_rand(2):
            h = simulate(gen.limit(40, wl["generator"]),
                         lambda c, inv: Op({**inv, "type": "ok"}))
        invokes = [o for o in h if o.get("type") == "invoke"]
        fs = {o["f"] for o in invokes}
        assert fs == {"read", "write"}
        for o in invokes:
            if o["f"] == "read":
                assert len(o["value"]) == 2

    def test_early_late_counts(self):
        h = H(lf_read(0, [(0, None), (1, None)])
              + lf_read(1, [(0, 1), (1, 1)]))
        r = long_fork.LongForkChecker(2).check({}, h)
        assert r["early-read-count"] == 1
        assert r["late-read-count"] == 1


# ---------------------------------------------------------------- causal


def causal_op(f, value=None, position=None, link=None):
    o = {"type": "ok", "process": 0, "f": f, "value": value}
    if position is not None:
        o["position"] = position
    o["link"] = link
    return o


class TestCausal:
    def test_valid_chain(self):
        h = H([causal_op("read-init", 0, position=1, link="init"),
               causal_op("write", 1, position=2, link=1),
               causal_op("read", 1, position=3, link=2),
               causal_op("write", 2, position=4, link=3),
               causal_op("read", 2, position=5, link=4)])
        r = causal.check().check({}, h)
        assert r["valid?"] is True

    def test_broken_link(self):
        h = H([causal_op("read-init", 0, position=1, link="init"),
               causal_op("write", 1, position=2, link=99)])
        r = causal.check().check({}, h)
        assert r["valid?"] is False
        assert "Cannot link" in r["error"]

    def test_stale_read(self):
        h = H([causal_op("read-init", 0, position=1, link="init"),
               causal_op("write", 1, position=2, link=1),
               causal_op("read", 0, position=3, link=2)])
        r = causal.check().check({}, h)
        assert r["valid?"] is False
        assert "can't read" in r["error"]

    def test_write_out_of_order(self):
        h = H([causal_op("read-init", 0, position=1, link="init"),
               causal_op("write", 2, position=2, link=1)])
        r = causal.check().check({}, h)
        assert r["valid?"] is False

    def test_workload_shape(self):
        wl = causal.workload({"time-limit": 60})
        assert "generator" in wl and "checker" in wl


# -------------------------------------------------------- causal-reverse


class TestCausalReverse:
    def test_valid(self):
        h = H([{"type": "invoke", "process": 0, "f": "write", "value": 0},
               {"type": "ok", "process": 0, "f": "write", "value": 0},
               {"type": "invoke", "process": 1, "f": "write", "value": 1},
               {"type": "ok", "process": 1, "f": "write", "value": 1},
               *ok_read(2, [0, 1])])
        r = causal_reverse.checker().check({}, h)
        assert r["valid?"] is True

    def test_missing_predecessor(self):
        # write 0 completes before write 1 invokes; a read sees 1 but not 0
        h = H([{"type": "invoke", "process": 0, "f": "write", "value": 0},
               {"type": "ok", "process": 0, "f": "write", "value": 0},
               {"type": "invoke", "process": 1, "f": "write", "value": 1},
               {"type": "ok", "process": 1, "f": "write", "value": 1},
               *ok_read(2, [1])])
        r = causal_reverse.checker().check({}, h)
        assert r["valid?"] is False
        assert r["errors"][0]["missing"] == [0]

    def test_concurrent_writes_ok_in_any_order(self):
        # both writes invoked before either completes: no precedence
        h = H([{"type": "invoke", "process": 0, "f": "write", "value": 0},
               {"type": "invoke", "process": 1, "f": "write", "value": 1},
               {"type": "ok", "process": 0, "f": "write", "value": 0},
               {"type": "ok", "process": 1, "f": "write", "value": 1},
               *ok_read(2, [1])])
        r = causal_reverse.checker().check({}, h)
        assert r["valid?"] is True


# ------------------------------------------------------------------ adya


class TestAdya:
    def test_valid_one_insert_per_key(self):
        h = H([{"type": "invoke", "process": 0, "f": "insert",
                "value": kv(0, [None, 1])},
               {"type": "ok", "process": 0, "f": "insert",
                "value": kv(0, [None, 1])},
               {"type": "invoke", "process": 1, "f": "insert",
                "value": kv(0, [2, None])},
               {"type": "fail", "process": 1, "f": "insert",
                "value": kv(0, [2, None])}])
        r = adya.g2_checker().check({}, h)
        assert r["valid?"] is True
        assert r["key-count"] == 1
        assert r["legal-count"] == 1

    def test_g2_double_insert(self):
        h = H([{"type": "invoke", "process": 0, "f": "insert",
                "value": kv(7, [None, 1])},
               {"type": "ok", "process": 0, "f": "insert",
                "value": kv(7, [None, 1])},
               {"type": "invoke", "process": 1, "f": "insert",
                "value": kv(7, [2, None])},
               {"type": "ok", "process": 1, "f": "insert",
                "value": kv(7, [2, None])}])
        r = adya.g2_checker().check({}, h)
        assert r["valid?"] is False
        assert r["illegal"] == {7: 2}

    def test_gen_unique_ids_two_per_key(self):
        wl = adya.workload()
        with fixed_rand(9):
            h = simulate(gen.limit(20, wl["generator"]),
                         lambda c, inv: Op({**inv, "type": "ok"}))
        ids = []
        per_key = {}
        for o in h:
            if o.get("type") == "invoke" and o.get("f") == "insert":
                v = o["value"]
                k, pair = v[0], v[1]
                per_key[k] = per_key.get(k, 0) + 1
                ids.append([x for x in pair if x is not None][0])
        assert len(ids) == len(set(ids))  # globally unique
        assert all(c <= 2 for c in per_key.values())


# ------------------------------------------------- set linearizable mode


class TestSetLinearizableDevice:
    def test_set_workload_linearizable_mode_rides_device(self):
        """The tendermint set workload's linearizable mode checks each
        per-key GSet sub-history through the device engine (analyzer
        :jax) — VERDICT round-2 ask #2: the set workload must not
        silently take the host WGL path now that GSet packs."""
        from jepsen_tpu.history import History, invoke_op, ok_op
        from jepsen_tpu.independent import KV
        from jepsen_tpu.tendermint import core as tm

        wl = tm.workload({"nodes": ["n1"], "workload": "set",
                          "linearizable": True})
        assert "linear" in wl["checker"]
        ops = []
        for k in (0, 1):
            for i in range(4):
                ops.append(invoke_op(k, "add", KV(k, i)))
                ops.append(ok_op(k, "add", KV(k, i)))
            ops.append(invoke_op(k, "read", KV(k, None)))
            ops.append(ok_op(k, "read", KV(k, list(range(4)))))
        h = History.wrap(ops).index()
        r = wl["checker"]["linear"].check({}, h)
        assert r["valid?"] is True, r
        for k, sub in r["results"].items():
            assert sub.get("analyzer") == "jax", (k, sub)

    def test_set_workload_linearizable_catches_lost_element(self):
        from jepsen_tpu.history import History, invoke_op, ok_op
        from jepsen_tpu.independent import KV
        from jepsen_tpu.tendermint import core as tm

        wl = tm.workload({"nodes": ["n1"], "workload": "set",
                          "linearizable": True})
        ops = [
            invoke_op(0, "add", KV(9, 1)), ok_op(0, "add", KV(9, 1)),
            invoke_op(0, "add", KV(9, 2)), ok_op(0, "add", KV(9, 2)),
            # read drops element 1 after both adds acked: not linearizable
            invoke_op(0, "read", KV(9, None)), ok_op(0, "read", KV(9, [2])),
        ]
        h = History.wrap(ops).index()
        r = wl["checker"]["linear"].check({}, h)
        assert r["valid?"] is False
        assert r["results"][9]["analyzer"] == "jax"


# ------------------------------------------------------------- cycle gen


class TestCycleGen:
    def test_cycle_restarts_exhausted_sequence(self):
        from jepsen_tpu.generator.testing import quick

        g = gen.limit(6, gen.cycle_gen([{"f": "a"}, {"f": "b"}]))
        h = quick(g)
        assert [o["f"] for o in h] == ["a", "b", "a", "b", "a", "b"]

    def test_causal_generator_advances_past_read_init(self):
        from jepsen_tpu.generator.testing import quick
        from jepsen_tpu import independent as ind

        wl = causal.workload({})
        h = quick(gen.limit(10, wl["generator"]))
        fs = [o["f"] for o in h if isinstance(o.get("process"), int)]
        assert "write" in fs and "read" in fs

    def test_causal_reverse_mix_keeps_reading(self):
        from jepsen_tpu.generator.testing import quick

        wl = causal_reverse.workload({"nodes": [1], "per-key-limit": 40})
        with fixed_rand(4):
            h = quick(gen.limit(40, wl["generator"]))
        fs = [o["f"] for o in h]
        assert fs.count("read") > 5
        assert fs.count("write") > 5
