"""Ops-surface suite (ISSUE 9 acceptance): Prometheus exposition
round-trip, /healthz degradation (breaker open, queue past
high-water, stale chip probe), /status per-key accounting, the
`jepsen status` client, the continuous probe watch, and the crash
flight recorder (dump on an injected wedge with tracing off, bounded
ring memory, off-by-default zero overhead).
"""

import json
import os
import re
import urllib.error
import urllib.request

import pytest

from jepsen_tpu import obs, resilience
from jepsen_tpu.envflags import EnvFlagError
from jepsen_tpu.histories import rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.obs import httpd as ops_httpd
from jepsen_tpu.obs.metrics import BUCKET_LADDER, hist_quantile
from jepsen_tpu.resilience import breaker as breaker_mod
from jepsen_tpu.resilience import supervisor as sup
from jepsen_tpu.serve import CheckerService


@pytest.fixture(autouse=True)
def _clean_obs_state(monkeypatch):
    """Every test starts with tracing/flight off, no fault plan, and
    closed breakers; the default registry is shared process state and
    deliberately NOT reset (metric names are cumulative by design —
    assertions below read deltas or their own names)."""
    for flag in ("JEPSEN_TPU_TRACE", "JEPSEN_TPU_FLIGHT_RECORDER",
                 "JEPSEN_TPU_FAULTS", "JEPSEN_TPU_WATCHDOG",
                 "JEPSEN_TPU_OPS_PORT", "JEPSEN_TPU_SEARCH_STATS"):
        monkeypatch.delenv(flag, raising=False)
    obs.reset()
    obs.flight_reset()
    obs.drain_search_stats()
    resilience.reset()
    yield
    obs.reset()
    obs.flight_reset()
    obs.drain_search_stats()
    resilience.reset()


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as err:
        return err.code, err.read().decode()


_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ([-+0-9.eE]+)$")


def _parse_prom(text):
    """A tiny exposition-format reader: {(name, labels): float},
    plus the {name: type} map from # TYPE lines. Raises on any line
    that is neither — the round-trip contract."""
    samples, types = {}, {}
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# TYPE "):
            _, _, name, typ = ln.split(" ")
            types[name] = typ
            continue
        if ln.startswith("#"):
            continue
        m = _SAMPLE.match(ln)
        assert m, f"unparseable exposition line: {ln!r}"
        samples[(m.group(1), m.group(2) or "")] = float(m.group(3))
    return samples, types


# ------------------------------------------------ exposition format


def test_prom_name_sanitization():
    assert ops_httpd.prom_name("serve.pending_ops") \
        == "jepsen_serve_pending_ops"
    assert ops_httpd.prom_name("resilience.breaker.cpu:0.state") \
        == "jepsen_resilience_breaker_cpu_0_state"
    assert ops_httpd.prom_name("9weird") == "jepsen_9weird"
    # every rendered name must be legal for Prometheus
    legal = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
    for raw in ("a.b", "a-b", "a b", "ä.ü", "x..y"):
        assert legal.match(ops_httpd.prom_name(raw)), raw


def test_render_prometheus_round_trip():
    reg = obs.Registry()
    reg.counter("t.count").inc(7)
    g = reg.gauge("t.depth")
    g.set(3)
    g.set(2)
    h = reg.histogram("t.secs")
    for v in (0.0005, 0.0005, 0.02, 5.0, 120.0):
        h.observe(v)
    text = ops_httpd.render_prometheus(reg.snapshot())
    samples, types = _parse_prom(text)
    assert types["jepsen_t_count"] == "counter"
    assert samples[("jepsen_t_count", "")] == 7
    assert types["jepsen_t_depth"] == "gauge"
    assert samples[("jepsen_t_depth", "")] == 2
    assert samples[("jepsen_t_depth_max", "")] == 3
    assert types["jepsen_t_secs"] == "histogram"
    # bucket cumulativity: counts are non-decreasing in le and the
    # +Inf bucket equals _count (120.0 lies past the ladder)
    buckets = [(float(lab[5:-2]), n) for (name, lab), n
               in samples.items()
               if name == "jepsen_t_secs_bucket" and "+Inf" not in lab]
    buckets.sort()
    assert [le for le, _ in buckets] == list(BUCKET_LADDER)
    counts = [n for _, n in buckets]
    assert counts == sorted(counts)
    assert counts[-1] == 4          # everything but the 120.0
    assert samples[("jepsen_t_secs_bucket", '{le="+Inf"}')] == 5
    assert samples[("jepsen_t_secs_count", "")] == 5
    assert samples[("jepsen_t_secs_sum", "")] == pytest.approx(125.021)


def test_histogram_buckets_answer_quantiles():
    reg = obs.Registry()
    h = reg.histogram("q.secs")
    for _ in range(99):
        h.observe(0.002)
    h.observe(8.0)
    snap = reg.snapshot()["q.secs"]
    assert hist_quantile(snap, 0.5) == 0.0025
    assert hist_quantile(snap, 0.99) == 0.0025
    assert hist_quantile(snap, 0.999) == 10.0
    assert hist_quantile(snap, 1.0) == 10.0
    # past-the-ladder observations fall back to the streaming max
    h2 = reg.histogram("q2.secs")
    h2.observe(500.0)
    assert hist_quantile(reg.snapshot()["q2.secs"], 0.99) == 500.0
    assert hist_quantile({"count": 0, "buckets": []}, 0.5) is None


def test_flight_recorder_flag_validation(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "nope")
    obs.reset()
    with pytest.raises(EnvFlagError):
        obs.flight_active()
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "-1")
    obs.reset()
    with pytest.raises(EnvFlagError):
        obs.flight_active()


# ------------------------------------------------ service + healthz


def _service(**kw):
    kw.setdefault("capacity", 256)
    kw.setdefault("dedupe", "sort")
    return CheckerService(CASRegister(), **kw)


def _ops_for(svc):
    return ops_httpd.start_ops_server(
        0, health_fn=svc.health, status_fn=svc.status,
        refresh_fn=svc.refresh_gauges)


def test_healthz_flips_on_breaker_open():
    svc = _service()
    ops = _ops_for(svc)
    try:
        code, body = _get(ops.url("/healthz"))
        assert code == 200 and json.loads(body)["ok"] is True
        br = breaker_mod.breaker_for("testbe", threshold=2,
                                     probe=lambda: False)
        br.record_failure("boom")
        br.record_failure("boom")
        assert br.state == breaker_mod.OPEN
        code, body = _get(ops.url("/healthz"))
        doc = json.loads(body)
        assert code == 503 and doc["ok"] is False
        assert doc["checks"]["breakers"]["ok"] is False
        assert doc["checks"]["breakers"]["states"]["testbe"] == "open"
        # the rest of the surface still answers while degraded
        code, _ = _get(ops.url("/metrics"))
        assert code == 200
        resilience.reset()
        code, body = _get(ops.url("/healthz"))
        assert code == 200 and json.loads(body)["ok"] is True
    finally:
        ops.close()
        svc.close()


def test_healthz_flips_on_queue_past_high_water():
    import threading
    h = list(rand_register_history(n_ops=32, n_processes=4, seed=5))
    # a STALLED worker (alive thread, never drains): admitted ops stay
    # pending so the queue level is exact, while the worker liveness
    # check stays green — isolating the high-water readiness flip
    svc = _service(start_worker=False, per_key_queue=64,
                   global_bound=64, high_water=8)
    release = threading.Event()
    svc._worker = threading.Thread(target=release.wait, daemon=True)
    svc._worker.start()
    ops = _ops_for(svc)
    try:
        code, body = _get(ops.url("/healthz"))
        assert code == 200 and json.loads(body)["ok"] is True
        r = svc.submit("k", h[:8])      # 8 ops: exactly at high-water
        assert r.get("accepted")
        code, body = _get(ops.url("/healthz"))
        doc = json.loads(body)
        assert code == 503 and doc["ok"] is False
        assert doc["checks"]["queue"]["ok"] is False
        assert doc["checks"]["queue"]["pending_ops"] == 8
        # and the shed path the high-water protects is live
        shed = svc.submit("k", h[8:16])
        assert shed.get("shed")
        st = json.loads(_get(ops.url("/status"))[1])
        assert st["keys"]['"k"']["acct"]["sheds"] == 1
    finally:
        release.set()
        ops.close()
        svc.close(drain=False)


def test_healthz_worker_death_and_probe_merge():
    svc = _service(start_worker=False)
    # no worker thread at all -> not ready (the liveness half of the
    # serve CLI's composition; the probe merge is the readiness half)
    doc = svc.health()
    assert doc["ok"] is False and doc["checks"]["worker"]["ok"] is False
    svc.close(drain=False)


def test_status_per_key_accounting_and_cli(capsys):
    h = list(rand_register_history(n_ops=24, n_processes=4, seed=11))
    svc = _service()
    ops = _ops_for(svc)
    try:
        assert svc.submit("k1", h[:12], wait=True,
                          timeout=120).get("valid?") is not None
        assert svc.submit(("pair", 2), h[12:], wait=True,
                          timeout=120).get("valid?") is not None
        code, body = _get(ops.url("/status"))
        assert code == 200
        doc = json.loads(body)
        row = doc["keys"]['"k1"']
        assert row["seq"] == 1 and row["state"] == "live"
        assert row["acct"] == {"deltas": 1, "ops": 12, "sheds": 0,
                               "replays": 0}
        assert '["pair" 2]' in doc["keys"]
        assert doc["worker_alive"] is True
        # SLO histograms moved (ack on admit, verdict on publish)
        snap = obs.registry().snapshot()
        assert snap["serve.ack_secs"]["count"] >= 2
        assert snap["serve.verdict_secs"]["count"] >= 2
        assert snap["serve.verdict_secs"]["buckets"][-1][1] \
            == snap["serve.verdict_secs"]["count"]
        # the `jepsen status` client renders the same surface
        rc = ops_httpd.status_main(["--port", str(ops.port)])
        out = capsys.readouterr().out
        assert rc == 0 and "READY" in out and '"k1"' in out
        rc = ops_httpd.status_main(["--port", str(ops.port), "--json"])
        j = json.loads(capsys.readouterr().out)
        assert j["health"]["ok"] is True and '"k1"' in j["status"]["keys"]
        rc = ops_httpd.status_main(["--port", str(ops.port),
                                    "--metrics"])
        out = capsys.readouterr().out
        assert rc == 0 and "jepsen_serve_deltas" in out
    finally:
        ops.close()
        svc.close()


def test_status_search_stats_row_and_metrics_quantiles(monkeypatch,
                                                       capsys):
    """ISSUE 10 wiring on the ops surface: with JEPSEN_TPU_SEARCH_
    STATS on, a served key's /status row carries its summarized
    lifetime stats block and /metrics serves jepsen_engine_search_*;
    `jepsen status --metrics` answers quantiles, `--raw` the
    exposition text. Flag off (every other test here): no "stats" key
    in any row — the schema pin rides the existing tests."""
    monkeypatch.setenv("JEPSEN_TPU_SEARCH_STATS", "1")
    h = list(rand_register_history(n_ops=24, n_processes=4, seed=12))
    svc = _service(dedupe="hash")
    ops = _ops_for(svc)
    try:
        assert svc.submit("k1", h, wait=True,
                          timeout=120).get("valid?") is not None
        code, body = _get(ops.url("/status"))
        row = json.loads(body)["keys"]['"k1"']
        st = row["stats"]
        assert st["events"] > 0 and st["frontier-peak"] > 0
        assert st["dedupe"] == "hash" and "probe-hist" in st
        # the summarized form stays scrape-sized: no trajectories
        assert "frontier-width" not in st
        code, body = _get(ops.url("/metrics"))
        assert "jepsen_engine_search_events" in body
        assert "jepsen_engine_search_frontier_peak" in body
        rc = ops_httpd.status_main(["--port", str(ops.port),
                                    "--metrics"])
        out = capsys.readouterr().out
        assert rc == 0 and "p99" in out and 'le="' not in out
        rc = ops_httpd.status_main(["--port", str(ops.port),
                                    "--metrics", "--raw"])
        out = capsys.readouterr().out
        assert rc == 0 and 'le="' in out
    finally:
        ops.close()
        svc.close()


def test_status_cli_unreachable_and_usage():
    # unused port: connection refused -> exit 2 (not a traceback)
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    assert ops_httpd.status_main(["--port", str(port)]) == 2
    assert ops_httpd.status_main([]) == 254          # no port anywhere
    assert ops_httpd.status_main(["--bogus"]) == 254
    # a server that answers but is NOT the ops endpoint (e.g. the web
    # results browser on serve's default port): exit 2 wrong-target,
    # not a traceback and not a phantom "degraded"
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Html(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            body = b"<html>not the ops endpoint</html>"
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # noqa: N802
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Html)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        hp = str(srv.server_address[1])
        assert ops_httpd.status_main(["--port", hp]) == 2
        assert ops_httpd.status_main(["--port", hp, "--metrics"]) == 2
    finally:
        srv.shutdown()
        srv.server_close()


def test_cli_forwards_status_subcommand(monkeypatch):
    from jepsen_tpu import cli
    seen = {}

    def fake_status_main(argv):
        seen["argv"] = argv
        return 0

    monkeypatch.setattr(ops_httpd, "status_main", fake_status_main)
    assert cli.run_cli(argv=["status", "--port", "1"]) == 0
    assert seen["argv"] == ["--port", "1"]


def test_ops_server_unknown_path_404():
    ops = ops_httpd.start_ops_server(0)
    try:
        code, body = _get(ops.url("/nope"))
        assert code == 404 and "endpoints" in json.loads(body)
        code, _ = _get(ops.url("/metrics"))
        assert code == 200
    finally:
        ops.close()


# ------------------------------------------------------ probe watch


def test_probe_watch_gauges_and_staleness():
    from jepsen_tpu import probe as probe_mod
    clock = [0.0]
    docs = [{"verdict": "healthy"}, {"verdict": "healthy"},
            {"verdict": "wedged"}]
    w = probe_mod.ProbeWatch(interval=10.0, timeout=5.0,
                             probe=lambda: docs.pop(0),
                             clock=lambda: clock[0])
    assert w.status()["ok"] is True      # first probe still in flight
    w.tick()
    assert obs.registry().snapshot()["probe.chip_healthy"]["value"] == 1
    st = w.status()
    assert st["ok"] is True and st["verdict"] == "healthy"
    clock[0] = 11.0
    w.tick()
    assert w.status()["last_ok_age_secs"] == 0.0
    clock[0] = 22.0
    w.tick()                              # the outage tick
    snap = obs.registry().snapshot()
    assert snap["probe.chip_healthy"]["value"] == 0
    st = w.status()
    assert st["ok"] is False and st["verdict"] == "wedged"
    assert st["last_ok_age_secs"] == 11.0
    # staleness alone degrades too: healthy-but-ancient is not ok
    w2 = probe_mod.ProbeWatch(interval=1.0, timeout=1.0,
                              probe=lambda: {"verdict": "healthy"},
                              clock=lambda: clock[0])
    w2.tick()
    clock[0] += 1000.0
    assert w2.status()["ok"] is False


def test_probe_watch_raising_probe_degrades_readiness():
    """A probe that RAISES every cycle (spawn failure) must degrade
    /healthz, not leave the first-tick ok=True grace in place
    forever."""
    from jepsen_tpu import probe as probe_mod

    def boom():
        raise OSError("cannot spawn probe child")

    w = probe_mod.ProbeWatch(interval=1.0, timeout=1.0, probe=boom,
                             clock=lambda: 0.0)
    w.tick()                              # absorbed, counted
    st = w.status()
    assert st["ticks"] == 1 and st["verdict"] == "probe-error"
    assert st["ok"] is False
    assert obs.registry().snapshot()["probe.chip_healthy"]["value"] == 0


def test_probe_watch_env_gate(monkeypatch):
    from jepsen_tpu import probe as probe_mod
    monkeypatch.delenv("JEPSEN_TPU_PROBE_INTERVAL", raising=False)
    assert probe_mod.start_watch_from_env() is None
    monkeypatch.setenv("JEPSEN_TPU_PROBE_INTERVAL", "0")
    assert probe_mod.start_watch_from_env() is None
    monkeypatch.setenv("JEPSEN_TPU_PROBE_INTERVAL", "soon")
    with pytest.raises(EnvFlagError):
        probe_mod.start_watch_from_env()


# -------------------------------------------------- flight recorder


def test_flight_dump_on_injected_wedge(tmp_path, monkeypatch):
    """The acceptance pin: tracing OFF, flight recorder armed, an
    injected wedge@dispatch leaves a Chrome-trace dump in the store
    dir."""
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "1")
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "wedge@dispatch:1")
    obs.reset()
    obs.flight_reset()
    obs.set_flight_dir(str(tmp_path))
    resilience.reset()
    assert not obs.enabled() and obs.flight_active()
    with obs.span("engine.pretend_search", key="k9"):
        pass
    with pytest.raises(sup.DispatchWedged):
        sup.dispatch("dispatch", lambda: 42)
    files = sorted(os.listdir(tmp_path))
    assert len(files) == 1 and files[0].endswith(".trace.json")
    assert "dispatch-wedged" in files[0]
    doc = json.load(open(tmp_path / files[0]))
    names = {ev.get("name") for ev in doc["traceEvents"]}
    assert "engine.pretend_search" in names
    fl = doc["flight"]
    assert fl["reason"].startswith("dispatch-wedged")
    assert fl["metrics_delta"]["resilience.watchdog_kills"]["value"] >= 1


def test_flight_dump_on_breaker_open(tmp_path, monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "1")
    obs.reset()
    obs.flight_reset()
    obs.set_flight_dir(str(tmp_path))
    resilience.reset()
    br = breaker_mod.breaker_for("flightbe", threshold=1,
                                 probe=lambda: False)
    br.record_failure("boom")
    files = [f for f in os.listdir(tmp_path) if "breaker-open" in f]
    assert len(files) == 1


def test_flight_ring_bounded_memory(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "16")
    obs.reset()
    obs.flight_reset()
    tr = obs.tracer()
    assert tr is not None and tr.flight_only
    for i in range(500):
        with obs.span("ring.spin", i=i):
            pass
    ring = tr.ring_spans()
    assert len(ring) == 16
    assert ring[-1].args["i"] == 499     # last N closed, oldest evicted
    assert tr.spans() == []              # the unbounded buffer NEVER
    # fills in flight-only mode — a week-long serve stays bounded
    # and run-dir exports stay off
    assert obs.export_run("store/should_not_exist") is None
    assert not os.path.exists("store/should_not_exist")


def test_flight_dump_cap(monkeypatch, tmp_path):
    from jepsen_tpu.obs import export as export_mod
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "4")
    obs.reset()
    obs.flight_reset()
    for i in range(export_mod.FLIGHT_MAX_DUMPS + 5):
        p = obs.flight_dump("storm", dest_dir=str(tmp_path))
        assert (p is None) == (i >= export_mod.FLIGHT_MAX_DUMPS)
    assert len(os.listdir(tmp_path)) == export_mod.FLIGHT_MAX_DUMPS


def test_flight_dump_failure_never_replaces_the_fault(tmp_path,
                                                      monkeypatch):
    """An unwritable flight dir must not turn a handled fault into an
    unhandled crash: the hook sites still raise their STRUCTURED
    errors (DispatchWedged here), and the dump failure is counted."""
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "1")
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "wedge@dispatch:1")
    obs.reset()
    obs.flight_reset()
    # a FILE where the dump dir should be: makedirs raises
    blocker = tmp_path / "flight"
    blocker.write_text("not a directory")
    obs.set_flight_dir(str(blocker))
    resilience.reset()
    before = obs.registry().snapshot().get(
        "obs.flight_dump_errors", {"value": 0})["value"]
    with pytest.raises(sup.DispatchWedged):   # NOT OSError
        sup.dispatch("dispatch", lambda: 42)
    snap = obs.registry().snapshot()
    assert snap["obs.flight_dump_errors"]["value"] == before + 1


def test_flight_off_is_the_historical_noop():
    """Off by default: span() is the no-op singleton (the <2µs pin in
    test_obs.py covers CPU), flight_dump is a None check, dispatch is
    the passthrough, and nothing exists on disk."""
    assert obs.tracer() is None
    s1, s2 = obs.span("a"), obs.span("b")
    assert s1 is s2                       # the singleton
    assert not obs.flight_active()
    assert obs.flight_dump("nothing") is None
    assert sup.dispatch("dispatch", lambda: 7) == 7


def test_flight_rides_full_tracing(monkeypatch):
    """TRACE=1 + FLIGHT_RECORDER: the ring retains spans across the
    per-run drain(), so a crash after N exported runs still dumps."""
    monkeypatch.setenv("JEPSEN_TPU_TRACE", "1")
    monkeypatch.setenv("JEPSEN_TPU_FLIGHT_RECORDER", "8")
    obs.reset()
    obs.flight_reset()
    tr = obs.tracer()
    assert obs.enabled() and obs.flight_active() and not tr.flight_only
    with obs.span("both.modes"):
        pass
    assert len(tr.spans()) == 1
    tr.drain()
    assert tr.spans() == []
    assert [s.name for s in tr.ring_spans()] == ["both.modes"]


# ------------------------------------------- labeled (tenant) series


def test_render_prometheus_labels_round_trip():
    """Registry names of the obs.labeled form render as REAL
    exposition labels sharing the base metric name, and
    parse_prometheus recovers each labeled series as its own
    quantile-answerable entry — the per-tenant SLO contract."""
    reg = obs.Registry()
    reg.counter("l.count").inc(7)
    reg.counter(obs.labeled("l.count", tenant="alice")).inc(2)
    reg.counter(obs.labeled("l.count", tenant="bob")).inc(3)
    h = reg.histogram(obs.labeled("l.secs", tenant="a-1"))
    for v in (0.0005, 0.02, 120.0):
        h.observe(v)
    reg.histogram("l.secs").observe(0.01)
    text = ops_httpd.render_prometheus(reg.snapshot())
    samples, types = _parse_prom(text)
    assert types["jepsen_l_count"] == "counter"
    assert samples[("jepsen_l_count", "")] == 7
    assert samples[("jepsen_l_count", '{tenant="alice"}')] == 2
    assert samples[("jepsen_l_count", '{tenant="bob"}')] == 3
    # exactly ONE TYPE line per metric name (the exposition grouping
    # rule), labeled and unlabeled series under it
    assert text.count("# TYPE jepsen_l_count ") == 1
    assert text.count("# TYPE jepsen_l_secs ") == 1
    assert samples[("jepsen_l_secs_bucket",
                    '{tenant="a-1",le="+Inf"}')] == 3
    assert samples[("jepsen_l_secs_count", '{tenant="a-1"}')] == 3
    assert samples[("jepsen_l_secs_max", '{tenant="a-1"}')] == 120.0
    parsed = ops_httpd.parse_prometheus(text)
    hh = parsed[obs.labeled("jepsen_l_secs", tenant="a-1")]
    assert hh["count"] == 3 and hh["max"] == 120.0
    from jepsen_tpu.obs.metrics import hist_quantile as hq
    assert hq(hh, 0.99) == 120.0   # past-ladder falls to the max twin
    # the unlabeled aggregate keeps its historical plain key
    assert parsed["jepsen_l_secs"]["count"] == 1
    # label values with quotes/backslashes survive the round trip
    reg.counter(obs.labeled("l.count", tenant='we"ird\\')).inc(1)
    parsed2 = ops_httpd.parse_prometheus(
        ops_httpd.render_prometheus(reg.snapshot()))
    assert parsed2[obs.labeled("jepsen_l_count",
                               tenant='we"ird\\')]["value"] == 1


def test_prometheus_label_value_escaping_round_trip():
    """Hostile label values — quotes, backslashes, newlines, and the
    cascade-prone backslash-then-n pair — must survive a
    render_prometheus -> parse_prometheus round trip byte-for-byte.
    (Sequential str.replace unescaping turned the two-character value
    `\\` + `n` into a literal newline; the single-pass unescaper this
    pins was the fix.)"""
    hostile = [
        'plain',
        'has"quote',
        'has\\backslash',
        'has\nnewline',
        'back\\nslash-n',          # the cascade case: `\` then `n`
        'mix"of\\all\nthree\\n',
        'trailing\\',
    ]
    reg = obs.Registry()
    for i, t in enumerate(hostile):
        reg.counter(obs.labeled("esc.count", tenant=t)).inc(i + 1)
        reg.histogram(obs.labeled("esc.secs",
                                  tenant=t)).observe(0.01 * (i + 1))
    text = ops_httpd.render_prometheus(reg.snapshot())
    # every sample stays a single exposition line (newlines escaped)
    for ln in text.splitlines():
        assert ln.startswith("#") or ln.count('{') <= 1
    parsed = ops_httpd.parse_prometheus(text)
    for i, t in enumerate(hostile):
        c = parsed[obs.labeled("jepsen_esc_count", tenant=t)]
        assert c["value"] == i + 1, (t, c)
        h = parsed[obs.labeled("jepsen_esc_secs", tenant=t)]
        assert h["count"] == 1, (t, h)
    # and the round trip is stable: render(parse(render)) keys match
    assert len([k for k in parsed if k.startswith("jepsen_esc_")]) \
        == 2 * len(hostile)


def test_labeled_split_labels_helpers():
    assert obs.labeled("a.b") == "a.b"
    assert obs.labeled("a.b", tenant="x") == "a.b[tenant=x]"
    assert obs.split_labels("a.b[tenant=x]") == ("a.b",
                                                 {"tenant": "x"})
    assert obs.split_labels("a.b") == ("a.b", {})
    base, labs = obs.split_labels(obs.labeled("n", a="1", b="2"))
    assert base == "n" and labs == {"a": "1", "b": "2"}


# --------------------------------------------- fleet (multi-replica)


def test_status_fleet_multi_addr(capsys):
    """`jepsen status --addr` (repeatable): one table per replica, a
    fleet summary, worst-of exit codes (unreachable beats degraded
    beats ready)."""
    import socket
    ok_srv = ops_httpd.OpsServer(
        port=0, health_fn=lambda: {"ok": True, "checks": {}},
        status_fn=lambda: {"keys": {}, "pending_ops": 0}).start()
    bad_srv = ops_httpd.OpsServer(
        port=0, health_fn=lambda: {"ok": False, "checks": {
            "worker": {"ok": False}}},
        status_fn=lambda: {"keys": {}, "pending_ops": 0}).start()
    # a port with nothing listening (bind-then-close reserves one)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    try:
        a_ok = f"127.0.0.1:{ok_srv.port}"
        a_bad = f"127.0.0.1:{bad_srv.port}"
        a_dead = f"127.0.0.1:{dead_port}"
        rc = ops_httpd.status_main(["--addr", a_ok, "--addr", a_bad,
                                    "--timeout", "5"])
        out = capsys.readouterr().out
        assert rc == 1   # one degraded, none unreachable
        assert f"== replica {a_ok} ==" in out
        assert "DEGRADED — failing checks: worker" in out
        assert "fleet: 1 ready, 1 degraded, 0 unreachable" in out
        rc = ops_httpd.status_main(["--addr", a_ok, "--addr", a_dead,
                                    "--timeout", "2"])
        out = capsys.readouterr().out
        assert rc == 2 and "UNREACHABLE" in out
        assert "fleet: 1 ready, 0 degraded, 1 unreachable" in out
        rc = ops_httpd.status_main(["--addr", a_ok, "--timeout", "5"])
        capsys.readouterr()
        assert rc == 0
        # --json emits the machine-readable fleet view the
        # supervisor and CI consume: per-replica state + worst-of
        # exit, same fetch path as the human table (fetch_replica)
        rc = ops_httpd.status_main(["--addr", a_ok, "--addr", a_dead,
                                    "--json", "--timeout", "2"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 2
        assert doc["replicas"][a_ok]["health"]["ok"] is True
        assert doc["replicas"][a_ok]["state"] == "ready"
        assert doc["replicas"][a_dead]["state"] == "unreachable"
        assert doc["fleet"] == {"ready": 1, "degraded": 0,
                                "unreachable": 1, "replicas": 2,
                                "exit": 2}
        rc = ops_httpd.status_main(["--addr", a_ok, "--json"])
        doc = json.loads(capsys.readouterr().out)
        assert rc == 0 and doc["fleet"]["exit"] == 0
        assert doc["replicas"][a_ok]["health"]["ok"] is True
        # malformed address is a usage error, not a crash
        assert ops_httpd.status_main(["--addr", "nope"]) == 254
        capsys.readouterr()
    finally:
        ok_srv.close()
        bad_srv.close()


def test_status_table_renders_tenants_section():
    status = {"keys": {}, "pending_ops": 0, "high_water": 10,
              "global_bound": 20, "keys_live": 0,
              "tenants": {"alice": {
                  "weight": 3, "pending_ops": 4, "pending_bound": 8,
                  "keys": 1, "wal_bytes": 2048,
                  "acct": {"sheds": 2, "deltas": 5, "ops": 20},
                  "ack_p99": 0.0025, "verdict_p99": None}}}
    health = {"ok": True, "checks": {}}
    out = ops_httpd.render_status_table(status, health)
    assert "tenant" in out and "alice" in out
    assert "0.0025" in out and "2.0KiB" in out
