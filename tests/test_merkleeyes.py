"""Integration tests for the native merkleeyes C++ component: builds
the binary with make, spawns it on a unix socket, and drives the full
tx surface from Python (parallel of merkleeyes/app_test.go:20-90, but
over the real socket server)."""

from __future__ import annotations

import shutil
import subprocess

import pytest

from jepsen_tpu.tendermint import gowire as w
from jepsen_tpu.tendermint import merkleeyes as me


def _toolchain():
    return shutil.which("g++") is not None and shutil.which("make")


pytestmark = pytest.mark.skipif(not _toolchain(),
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("merkleeyes")
    with me.LocalServer(sock_path=str(d / "me.sock"),
                        wal_path=str(d / "me.wal")) as srv:
        yield srv


def test_cpp_unit_suite_passes():
    r = subprocess.run(["make", "-s", "test"], cwd=me.NATIVE_DIR,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stdout


def test_echo_info(server):
    with server.client() as cl:
        assert cl.echo(b"hello") == b"hello"
        height, apphash = cl.info()
        assert height >= 0 and len(apphash) == 32


def test_kv_lifecycle(server):
    with server.client() as cl:
        r = cl.tx_commit(w.set_tx("name", "satoshi"))
        assert r.ok, r
        q = cl.query("/key", b"name")
        assert q.ok and q.value == b"satoshi"

        # CAS success then failure (app.go:308-352)
        assert cl.tx_commit(w.cas_tx("name", "satoshi", "nakamoto")).ok
        bad = cl.tx_commit(w.cas_tx("name", "satoshi", "x"))
        assert bad.code == me.CODE_UNAUTHORIZED
        assert "not" in bad.log
        q = cl.query("/key", b"name")
        assert q.value == b"nakamoto"

        # Get via DeliverTx sees working state (app.go:291-306)
        cl.begin_block()
        assert cl.deliver_tx(w.set_tx("fresh", "v")).ok
        g = cl.deliver_tx(w.get_tx("fresh"))
        assert g.ok and g.data == b"v"
        # but query (committed) doesn't see it yet
        assert cl.query("/key", b"fresh").code == me.CODE_BASE_UNKNOWN_ADDRESS
        cl.end_block()
        cl.commit()
        assert cl.query("/key", b"fresh").ok

        # Rm
        assert cl.tx_commit(w.rm_tx("fresh")).ok
        assert cl.query("/key", b"fresh").code == me.CODE_BASE_UNKNOWN_ADDRESS
        assert cl.tx_commit(w.rm_tx("fresh")).code == \
            me.CODE_BASE_UNKNOWN_ADDRESS


def test_nonce_dedupe(server):
    with server.client() as cl:
        n = w.nonce()
        assert cl.tx_commit(w.set_tx("k", "1", nonce_=n)).ok
        dup = cl.tx_commit(w.set_tx("k", "2", nonce_=n))
        assert dup.code == me.CODE_BAD_NONCE
        assert cl.query("/key", b"k").value == b"1"


def test_query_paths(server):
    with server.client() as cl:
        cl.tx_commit(w.set_tx("qq", "vv"))
        size_q = cl.query("/size")
        assert size_q.ok
        size, _ = w.read_varint(size_q.value)
        assert size >= 2  # keys + nonces share the tree

        idx_q = cl.query("/index", w.varint(0))
        assert idx_q.ok and idx_q.key

        bogus = cl.query("/bogus")
        assert bogus.code == me.CODE_UNKNOWN_REQUEST


def test_valset(server):
    with server.client() as cl:
        pk = bytes([0xAB]) * 32
        v0 = cl.tx_commit(w.valset_read_tx())
        assert v0.ok
        import json
        before = json.loads(v0.data)

        cl.begin_block()
        assert cl.deliver_tx(w.valset_change_tx(pk, 7)).ok
        updates = cl.end_block()
        assert (pk, 7) in updates
        cl.commit()

        after = json.loads(cl.tx_commit(w.valset_read_tx()).data)
        assert after["version"] == before["version"] + 1
        assert {"pub_key": pk.hex().upper(), "power": 7} in \
            after["validators"]

        # valset CAS with stale version rejected
        stale = cl.tx_commit(
            w.valset_cas_tx(before["version"], bytes([0xCD]) * 32, 3))
        assert stale.code == me.CODE_UNAUTHORIZED
        ok = cl.tx_commit(
            w.valset_cas_tx(after["version"], bytes([0xCD]) * 32, 3))
        assert ok.ok


def test_malformed_txs(server):
    with server.client() as cl:
        # too short
        assert cl.deliver_tx(b"\x01\x02").code == me.CODE_ENCODING_ERROR
        # unknown type byte
        r = cl.tx_commit(w.tx(0x63))
        assert r.code == me.CODE_UNKNOWN_TX_TYPE
        # trailing garbage on a Get
        r = cl.tx_commit(w.get_tx("k") + b"junk")
        assert r.code == me.CODE_ENCODING_ERROR


def test_wal_survives_restart(tmp_path):
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.tx_commit(w.set_tx("persist", "yes")).ok
            h1, hash1 = cl.info()
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            h2, hash2 = cl.info()
            assert h2 == h1
            assert hash2 == hash1  # replay reproduces the app hash
            assert cl.query("/key", b"persist").value == b"yes"


def test_wal_replays_malformed_tx_nonce(tmp_path):
    """A tx that marks its nonce but then fails to parse (unknown type
    byte, code 5) mutates the working tree; the WAL must record it so
    replay reproduces the exact pre-crash state — including rejecting a
    later reuse of that nonce."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    nonce = bytes(range(12))
    bad_tx = nonce + bytes([0x99])  # unknown tx type
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            cl.begin_block()
            assert cl.deliver_tx(bad_tx).code == me.CODE_UNKNOWN_TX_TYPE
            cl.end_block()
            cl.commit()
            h1, hash1 = cl.info()
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            h2, hash2 = cl.info()
            assert (h2, hash2) == (h1, hash1)
            # the malformed tx's nonce survived the replay
            r = cl.tx_commit(w.set_tx("x", "y", nonce_=nonce))
            assert r.code == me.CODE_BAD_NONCE


def test_wal_preserves_height_across_empty_blocks(tmp_path):
    """Empty blocks bump the committed height; the WAL writes a frame
    per commit so the replayed height matches the pre-crash value."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.tx_commit(w.set_tx("k", "v")).ok
            for _ in range(3):  # three empty blocks
                cl.begin_block()
                cl.end_block()
                cl.commit()
            h1, hash1 = cl.info()
            assert h1 == 4
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.info() == (h1, hash1)


def test_wal_truncation_rolls_back_blocks(tmp_path):
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.tx_commit(w.set_tx("a", "1")).ok
            assert cl.tx_commit(w.set_tx("b", "2")).ok
    # chop mid-frame, as the truncate nemesis does
    data = open(wal, "rb").read()
    open(wal, "wb").write(data[:-3])
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.query("/key", b"a").value == b"1"
            assert cl.query("/key", b"b").code == \
                me.CODE_BASE_UNKNOWN_ADDRESS


def test_wal_mid_file_corruption_refuses_to_run(tmp_path):
    """A bit flip inside a committed frame is corruption, not the
    nemesis's tail truncation — the server must refuse to run rather
    than silently discard committed history."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.tx_commit(w.set_tx("a", "1")).ok
            assert cl.tx_commit(w.set_tx("b", "2")).ok
    data = bytearray(open(wal, "rb").read())
    data[6] ^= 0xFF  # flip a byte inside the first frame
    open(wal, "wb").write(bytes(data))
    with pytest.raises(RuntimeError, match="exited"):
        me.LocalServer(sock_path=sock, wal_path=wal).start()


def test_wal_foreign_file_refuses_to_run(tmp_path):
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    open(wal, "wb").write(b"this is not a merkleeyes wal")
    with pytest.raises(RuntimeError, match="exited"):
        me.LocalServer(sock_path=sock, wal_path=wal).start()


def test_wal_truncate_then_commit_then_crash(tmp_path):
    """The double-crash sequence the truncate nemesis drives: chop the
    WAL mid-frame, restart, commit new blocks, restart again. The first
    restart must drop the partial frame from the file — otherwise the
    post-recovery frames land after garbage and the second replay
    mis-parses the boundary."""
    sock = str(tmp_path / "s.sock")
    wal = str(tmp_path / "w.wal")
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.tx_commit(w.set_tx("a", "1")).ok
            assert cl.tx_commit(w.set_tx("b", "2")).ok
    data = open(wal, "rb").read()
    open(wal, "wb").write(data[:-3])  # chop mid-frame
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.query("/key", b"b").code == me.CODE_BASE_UNKNOWN_ADDRESS
            assert cl.tx_commit(w.set_tx("c", "3")).ok  # post-recovery commit
            h1, hash1 = cl.info()
    with me.LocalServer(sock_path=sock, wal_path=wal) as srv:
        with srv.client() as cl:
            assert cl.info() == (h1, hash1)
            assert cl.query("/key", b"a").value == b"1"
            assert cl.query("/key", b"c").value == b"3"


def test_concurrent_clients(server):
    import threading
    errs = []

    def worker(i):
        try:
            with server.client() as cl:
                for j in range(20):
                    r = cl.tx_commit(w.set_tx(f"c{i}", f"v{j}"))
                    assert r.ok
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs
    with server.client() as cl:
        for i in range(4):
            assert cl.query("/key", f"c{i}".encode()).value == b"v19"
