"""Pallas closure kernel (parallel.pallas_kernels) — interpreter-mode
differential tests on the CPU backend. Three oracles:

1. a pure-Python SEMANTIC fixpoint over (state, mask) pairs, written
   from the closure's definition, not its bit-twiddling realisation;
2. the XLA bitdense closure (same algebra, different execution);
3. the host WGL engine, via full check_encoded_bitdense runs with the
   pallas path forced on.
"""

import numpy as np
import pytest

from jepsen_tpu.parallel import bitdense, pallas_kernels as pk

FULL = np.uint32(0xFFFFFFFF)


def _semantic_fixpoint(sel, B, C):
    """Reference closure: from every reachable (state s, mask m), for
    every slot j not in m with a legal transition s->t, (t, m | 1<<j)
    is reachable. Iterate to fixpoint. sel [C,S,S], B [S,W] words."""
    S, W = B.shape
    reach = set()
    for s in range(S):
        for w in range(W):
            word = int(B[s, w])
            for b in range(32):
                if (word >> b) & 1:
                    reach.add((s, w * 32 + b))
    changed = True
    while changed:
        changed = False
        for (s, m) in list(reach):
            for j in range(C):
                if (m >> j) & 1:
                    continue
                for t in range(S):
                    if sel[j, s, t] and (t, m | (1 << j)) not in reach:
                        reach.add((t, m | (1 << j)))
                        changed = True
    out = np.zeros((S, W), np.uint32)
    for (s, m) in reach:
        out[s, m // 32] |= np.uint32(1) << np.uint32(m % 32)
    return out


from jepsen_tpu.histories import with_impossible_read as _with_impossible_read


def _rand_case(seed, S=5, C=12, n_seeds=3, p_legal=0.08):
    rng = np.random.default_rng(seed)
    W = (1 << C) // 32
    sel = np.where(rng.random((C, S, S)) < p_legal, FULL,
                   np.uint32(0)).astype(np.uint32)
    B = np.zeros((S, W), np.uint32)
    for _ in range(n_seeds):
        s, m = rng.integers(S), rng.integers(1 << C)
        B[s, m // 32] |= np.uint32(1) << np.uint32(m % 32)
    return sel, B, C


@pytest.mark.parametrize("seed", range(4))
def test_pallas_closure_vs_semantic_oracle(seed):
    sel, B, C = _rand_case(seed)
    got = np.asarray(pk.closure_fixpoint(sel, B, C, interpret=True))
    want = _semantic_fixpoint(sel, B, C)
    assert (got == want).all(), f"seed {seed}: {int((got != want).sum())} "\
                                f"words differ"


def test_xor_shuffle_is_the_xor_permutation():
    """_xor_shuffle must realise y[..., w] = x[..., w ^ jb] exactly,
    for every power-of-two stride the kernel uses (jb = 1 .. W/2).
    Guards the r5 rewrite: the original reshape/flip spelling was
    semantically identical but uncompilable by Mosaic (no `rev`, no
    4-D lane reshape), so the spelling changed on-chip — this pins the
    permutation itself, independent of the full-kernel differential."""
    import jax

    rng = np.random.default_rng(11)
    for S, W in ((13, 256), (6, 128)):
        x = rng.integers(0, 2**32, size=(S, W), dtype=np.uint32)
        jb = 1
        while jb <= W // 2:
            got = np.asarray(jax.jit(
                pk._xor_shuffle, static_argnums=1)(x, jb))
            want = x[:, np.arange(W) ^ jb]
            np.testing.assert_array_equal(got, want, err_msg=f"jb={jb}")
            jb <<= 1


def test_pallas_supported_gate():
    assert pk.supported(6, 12)       # W=128
    assert not pk.supported(6, 11)   # W=64: below one lane tile
    assert not pk.supported(100, 12) # S too large to unroll


def test_bitdense_pallas_path_differential():
    """Full engine runs with the pallas closure forced on vs the XLA
    closure and the host oracle — valid and invalid histories. C must
    be >= 12 for kernel support, so the histories carry 11 crashed
    writes to widen the slot window."""
    from jepsen_tpu.checker import wgl
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode as enc_mod

    h = adversarial_register_history(n_ops=60, k_crashed=11, seed=5)
    e = enc_mod.encode(CASRegister(), h)
    assert pk.supported(bitdense.n_states(e), e.n_slots), \
        (bitdense.n_states(e), e.n_slots)
    r_xla = bitdense.check_encoded_bitdense(e, use_pallas=False)
    r_pl = bitdense.check_encoded_bitdense(e, use_pallas=True)
    assert r_pl["closure"] == "pallas" and r_xla["closure"] == "xla-while"
    assert r_xla["valid?"] is r_pl["valid?"] is True

    # invalid: impossible read appended
    hb = _with_impossible_read(h)
    eb = enc_mod.encode(CASRegister(), hb)
    rb_xla = bitdense.check_encoded_bitdense(eb, use_pallas=False)
    rb_pl = bitdense.check_encoded_bitdense(eb, use_pallas=True)
    assert rb_xla["valid?"] is rb_pl["valid?"] is False
    assert rb_xla["fail-event"] == rb_pl["fail-event"]
    assert wgl.analysis(CASRegister(), hb)["valid?"] is False


def test_batch_pallas_path_differential():
    """check_batch_bitdense with the vmapped pallas closure vs the XLA
    closure on a mixed valid/invalid key batch (padded C >= 12 for
    kernel support)."""
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode as enc_mod

    encs = []
    for seed in range(3):
        h = adversarial_register_history(n_ops=40, k_crashed=11,
                                         seed=seed)
        encs.append(enc_mod.encode(CASRegister(), h))
    # one invalid key: impossible read appended
    h = adversarial_register_history(n_ops=40, k_crashed=11, seed=9)
    encs.append(enc_mod.encode(CASRegister(), _with_impossible_read(h)))

    # the differential is vacuous unless the PADDED batch dims clear
    # the kernel's support gate (check_batch downgrades silently)
    S_pad = max(bitdense.n_states(e) for e in encs)
    C_pad = max(5, max(e.n_slots for e in encs))
    assert pk.supported(S_pad, C_pad), (S_pad, C_pad)

    rs_xla = bitdense.check_batch_bitdense(encs, use_pallas=False)
    rs_pl = bitdense.check_batch_bitdense(encs, use_pallas=True)
    assert all(r["closure"] == "xla-while" for r in rs_xla)
    assert all(r["closure"] == "pallas" for r in rs_pl)
    assert [r["valid?"] for r in rs_xla] == [True, True, True, False]
    for rx, rp in zip(rs_xla, rs_pl):
        assert rx["valid?"] is rp["valid?"]
        assert rx.get("fail-event") == rp.get("fail-event")


def test_axon_platform_counts_as_tpu():
    """The axon PJRT plugin registers its backend under the name
    "axon"; platform gates must treat it as the real chip — a literal
    == "tpu" check would run pallas in interpret mode ON the TPU."""
    assert bitdense.is_tpu_platform("tpu")
    assert bitdense.is_tpu_platform("axon")
    assert not bitdense.is_tpu_platform("cpu")
    assert not bitdense.is_tpu_platform("cuda")
    # the gate's interpret decision follows it
    _, interp = bitdense._resolve_use_pallas(True, 17, 12, "axon")
    assert interp is False
    _, interp = bitdense._resolve_use_pallas(True, 17, 12, "cpu")
    assert interp is True


# --------------------------------------------- SPMD / mesh lowering

def test_pallas_closure_under_shard_map_interpret():
    """The kernel's per-device SPMD lowering, exercised the way a
    mesh-sharded TPU batch would run it: shard_map over the 8-device
    CPU mesh, one closure per local key, interpret mode. Must equal
    the same kernel run unsharded per key. (On-chip non-interpret A/B
    is the remaining hardware-only step — PARITY §2.20.)"""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    S, C = 8, 12
    K = 8
    sels, bs = [], []
    for k in range(K):
        sel, B, _ = _rand_case(100 + k, S=S, C=C)
        sels.append(sel)
        bs.append(B)
    sel_all = np.stack(sels)           # [K, C, S, S]
    b_all = np.stack(bs)               # [K, S, W]

    mesh = Mesh(np.array(jax.devices()[:8]), ("keys",))

    def per_shard(sel_k, b_k):
        # local leading axis: K/8 = 1 key per device
        return jax.vmap(
            lambda s, b: pk.closure_call(s, b, C, interpret=True)
        )(sel_k, b_k)

    # check_vma=False: pallas_call's ShapeDtypeStruct carries no vma
    # annotation; the value check would reject it under shard_map.
    # Routed through the engine's jax-version shim (jax.shard_map vs
    # jax.experimental.shard_map/check_rep) like every sharded entry
    # point.
    from jepsen_tpu.parallel.sharded import _shard_map
    sharded_fn = jax.jit(_shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("keys"), P("keys")), out_specs=P("keys"),
        check_vma=False))
    out_sharded = np.asarray(sharded_fn(sel_all, b_all))

    for k in range(K):
        ref = np.asarray(pk.closure_fixpoint(sel_all[k], b_all[k], C,
                                             interpret=True))
        np.testing.assert_array_equal(out_sharded[k], ref)


def test_batch_pallas_on_mesh_differential():
    """check_batch_bitdense with the key axis sharded over the 8-device
    mesh and the pallas closure forced on (on this CPU mesh the default
    resolves to XLA; on a real-TPU mesh it is pallas since the r5
    on-chip A/B): verdicts and fail events must match the XLA path on
    the same mesh."""
    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode as enc_mod

    encs = []
    for seed in range(7):
        h = adversarial_register_history(n_ops=40, k_crashed=11,
                                         seed=seed)
        encs.append(enc_mod.encode(CASRegister(), h))
    h = adversarial_register_history(n_ops=40, k_crashed=11, seed=9)
    encs.append(enc_mod.encode(CASRegister(), _with_impossible_read(h)))
    assert len(encs) == 8              # divisible: key axis SHARDS

    S_pad = max(bitdense.n_states(e) for e in encs)
    C_pad = max(5, max(e.n_slots for e in encs))
    assert pk.supported(S_pad, C_pad), (S_pad, C_pad)

    mesh = Mesh(np.array(jax.devices()[:8]), ("keys",))
    rs_xla = bitdense.check_batch_bitdense(encs, mesh=mesh,
                                           use_pallas=False)
    rs_pl = bitdense.check_batch_bitdense(encs, mesh=mesh,
                                          use_pallas=True)
    assert all(r["closure"] == "pallas" for r in rs_pl)
    assert [r["valid?"] for r in rs_pl] == [r["valid?"] for r in rs_xla]
    assert rs_pl[-1]["valid?"] is False
    for rx, rp in zip(rs_xla, rs_pl):
        assert rx.get("fail-event") == rp.get("fail-event")

    # default resolution after the r5 on-chip A/B verdict (default-on,
    # every shape won, zero disagreements): a real-TPU platform gets
    # pallas non-interpret by default, JEPSEN_TPU_PALLAS=0 opts out,
    # and non-TPU platforms stay off unless the flag forces interpret
    import os as _os
    import unittest.mock as mock
    env = dict(_os.environ)
    env.pop("JEPSEN_TPU_PALLAS", None)   # hermetic: a developer's
    with mock.patch.dict(_os.environ, env, clear=True):   # exported
        # flag must not flip these default-resolution asserts
        assert bitdense._resolve_use_pallas(None, 17, 12, "axon") \
            == (True, False)
        assert bitdense._resolve_use_pallas(None, 17, 12, "cpu") \
            == (False, True)
        # unsupported shapes still downgrade regardless of platform
        assert bitdense._resolve_use_pallas(None, 128, 12, "axon")[0] \
            is False
    with mock.patch.dict(_os.environ, {"JEPSEN_TPU_PALLAS": "0"}):
        assert bitdense._resolve_use_pallas(None, 17, 12, "axon") \
            == (False, False)
    with mock.patch.dict(_os.environ, {"JEPSEN_TPU_PALLAS": "1"}):
        assert bitdense._resolve_use_pallas(None, 17, 12, "cpu") \
            == (True, True)


@pytest.mark.slow
def test_fori_closure_mode_differential():
    """The fixed-trip fori closure must be verdict- and fail-event-
    equal to the converge-and-stop while closure (its trip bound
    ceil(C/2) double-expansions is a worst-case convergence proof — a
    wrong bound shows up here as a missed expansion on deep chains).

    slow-marked: ~3 minutes of k=11 adversarial + crashy-FIFO device
    searches differentially testing an OPT-IN closure mode (fori lost
    the r5 on-chip A/B 0.3x and stays non-default; fori correctness
    also rides tools/perf_ab.py's gate on every measured run) — the
    single second-largest sink in the default suite."""
    from jepsen_tpu.histories import (adversarial_register_history,
                                      rand_fifo_history)
    from jepsen_tpu.models import CASRegister, FIFOQueue
    from jepsen_tpu.parallel import encode as enc_mod

    cases = []
    for seed in range(3):
        h = adversarial_register_history(n_ops=60, k_crashed=11,
                                         seed=seed)
        cases.append((CASRegister(), h))
    cases.append((CASRegister(), _with_impossible_read(
        adversarial_register_history(n_ops=60, k_crashed=11, seed=9))))
    # deep-chain shape: crashy FIFO keys linearize long suffixes at
    # once, the regime where an undersized trip bound would diverge
    for seed in (1, 5):
        cases.append((FIFOQueue(),
                      rand_fifo_history(n_ops=24, n_processes=4,
                                        n_values=3, crash_p=0.15,
                                        seed=seed)))
    for model, h in cases:
        e = enc_mod.encode(model, h)
        rw = bitdense.check_encoded_bitdense(e, closure_mode="while")
        rf = bitdense.check_encoded_bitdense(e, closure_mode="fori")
        assert rw["closure"] == "xla-while"
        assert rf["closure"] == "xla-fori"
        assert rw["valid?"] is rf["valid?"], (rw, rf)
        assert rw.get("fail-event") == rf.get("fail-event")


def test_batch_pallas_multidevice_mesh_falls_back_to_xla(monkeypatch):
    """An unmeasured multi-device Mosaic lowering gap on the DEFAULT
    pallas path must degrade to the XLA closure with a note — not
    crash the batch check. Explicit use_pallas=True (kernel tests, A/B
    runs) and single-device runs must still see the real error.
    Simulated by failing the engine call whenever the pallas variant
    is requested (the real trigger needs multi-chip TPU hardware)."""
    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode as enc_mod

    hs = [adversarial_register_history(n_ops=40, k_crashed=11, seed=s)
          for s in range(3)]
    hs.append(_with_impossible_read(hs[0]))
    encs = [enc_mod.encode(CASRegister(), h) for h in hs]
    mesh = Mesh(np.array(jax.devices()[:4]), ("keys",))

    baseline = bitdense.check_batch_bitdense(encs, mesh=mesh,
                                             use_pallas=False)

    real = bitdense._check_bitdense_batch

    def failing_on_pallas(*args, **kw):
        if args[6]:  # use_pallas
            raise RuntimeError("Mosaic lowering gap (simulated)")
        return real(*args, **kw)

    monkeypatch.setattr(bitdense, "_check_bitdense_batch",
                        failing_on_pallas)
    # true DEFAULT path: use_pallas=None, no env flag, and the
    # platform gate resolving ON (as it would on a real TPU mesh)
    monkeypatch.delenv("JEPSEN_TPU_PALLAS", raising=False)
    monkeypatch.setattr(bitdense, "_resolve_use_pallas",
                        lambda up, S, C, platform: (True, True))
    rs = bitdense.check_batch_bitdense(encs, mesh=mesh)
    assert [r["valid?"] for r in rs] == [r["valid?"] for r in baseline]
    assert rs[-1]["valid?"] is False
    assert rs[-1]["fail-event"] == baseline[-1]["fail-event"]
    for r in rs:
        assert r["closure"] == "xla-while"
        assert "pallas closure failed on a 4-device mesh" \
            in r["closure-note"]

    # explicit request: the error must surface
    with pytest.raises(RuntimeError, match="Mosaic"):
        bitdense.check_batch_bitdense(encs, mesh=mesh, use_pallas=True)

    # ...and a malformed env flag (never consulted when the arg is
    # explicit) must not shadow the real pallas error in the handler
    monkeypatch.setenv("JEPSEN_TPU_PALLAS", "yes")
    with pytest.raises(RuntimeError, match="Mosaic"):
        bitdense.check_batch_bitdense(encs, mesh=mesh, use_pallas=True)
    monkeypatch.delenv("JEPSEN_TPU_PALLAS")

    # env-forced =1 is a force ("=1 forces it on" is the documented
    # contract): it must surface the error too, not degrade silently
    monkeypatch.setenv("JEPSEN_TPU_PALLAS", "1")
    with pytest.raises(RuntimeError, match="Mosaic"):
        bitdense.check_batch_bitdense(encs, mesh=mesh)
    monkeypatch.delenv("JEPSEN_TPU_PALLAS")

    # single-device (no mesh): the default path must also surface it —
    # the 1-device config IS the measured one, a failure there is news
    with pytest.raises(RuntimeError, match="Mosaic"):
        bitdense.check_batch_bitdense(encs)
