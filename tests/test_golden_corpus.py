"""Golden EDN history corpus — every engine against recorded verdicts.

The reference's checker tests are hand-written history fixtures with
exact expected results (SURVEY.md §4.3/§4.8: "golden histories,
including knossos's known-valid/invalid corpora"). knossos's own
data/*.edn files are external to the snapshot, so this corpus is
generated in-repo (tests/data/golden/, verdicts recorded in
manifest.json at generation time from the host WGL oracle) in the
reference's on-disk EDN format — the same format `lein run analyze`
re-checks. The test round-trips each file through History.from_edn and
requires EVERY engine — host wgl / linear / packed, the device
sparse/bitdense dispatch, and (in the opt-in fuzz tier) the
mesh-sharded frontier engine — to reproduce the recorded verdict.
"""

import json
import pathlib

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from jepsen_tpu.checker import linear, linear_packed, wgl
from jepsen_tpu.history import History
from jepsen_tpu.models import (
    CASRegister, FIFOQueue, GSet, Mutex, UnorderedQueue)
from jepsen_tpu.parallel import engine, sharded

GOLDEN = pathlib.Path(__file__).parent / "data" / "golden"
MANIFEST = json.loads((GOLDEN / "manifest.json").read_text())
MODELS = {"cas-register": CASRegister, "fifo-queue": FIFOQueue,
          "unordered-queue": UnorderedQueue, "set": GSet, "mutex": Mutex}


@pytest.mark.parametrize("entry", MANIFEST,
                         ids=[e["file"] for e in MANIFEST])
def test_golden_corpus_all_engines(entry):
    h = History.from_edn((GOLDEN / entry["file"]).read_text()).index()
    assert len(h) == entry["ops"], "corpus file round-trip lost ops"
    model = MODELS[entry["model"]]()
    want = entry["valid"]

    assert wgl.analysis(model, h)["valid?"] is want, "wgl"
    assert linear.analysis(model, h)["valid?"] is want, "linear"
    assert linear_packed.analysis(model, h)["valid?"] is want, "packed"
    r = engine.analysis(model, h)
    assert r["valid?"] is want, f"device: {r}"
    assert "fallback" not in r, r
    if want is False:
        # invalid verdicts must carry a counterexample op
        assert r.get("op"), r


@pytest.mark.fuzz
@pytest.mark.parametrize("entry", [e for e in MANIFEST
                                   if "1k-crashheavy" in e["file"]],
                         ids=lambda e: e["file"])
def test_golden_corpus_pallas_closure(entry):
    """The corpus entries wide enough for the VMEM kernel (C >= 12 —
    the two 1k crash-heavy registers) must reproduce their recorded
    verdicts through the forced pallas path (interpret mode on this
    CPU backend; the closure label proves no silent downgrade). Pallas
    is the real-TPU default since the r5 on-chip A/B, so the corpus
    contract extends to it."""
    from jepsen_tpu.parallel import bitdense, pallas_kernels as pk
    from jepsen_tpu.parallel import encode as enc_mod

    h = History.from_edn((GOLDEN / entry["file"]).read_text()).index()
    e = enc_mod.encode(MODELS[entry["model"]](), h)
    S, C = bitdense.n_states(e), max(5, e.n_slots)
    assert pk.supported(S, C), (S, C)
    r = bitdense.check_encoded_bitdense(e, use_pallas=True)
    assert r["closure"] == "pallas", r
    assert r["valid?"] is entry["valid"], r
    if entry["valid"] is False:
        r_x = bitdense.check_encoded_bitdense(e, use_pallas=False)
        assert r.get("fail-event") == r_x.get("fail-event"), (r, r_x)


@pytest.mark.fuzz
@pytest.mark.parametrize("entry", MANIFEST,
                         ids=[e["file"] for e in MANIFEST])
def test_golden_corpus_sharded_engine(entry):
    """Every corpus verdict must also reproduce with the frontier
    sharded across the 8-device mesh (opt-in tier: one sharded compile
    per shape is too slow for the default suite)."""
    if not entry.get("sharded_tier", True):
        pytest.skip(entry["sharded_tier_skip_reason"])
    h = History.from_edn((GOLDEN / entry["file"]).read_text()).index()
    model = MODELS[entry["model"]]()
    mesh = Mesh(np.array(jax.devices()[:8]), ("frontier",))
    r = sharded.analysis(model, h, mesh, capacity=64 * 8)
    assert r["valid?"] is entry["valid"], r
    # a host fallback would re-run the oracle that MADE the manifest —
    # meaningless; this tier must exercise the sharded engine itself
    assert "fallback" not in r, r
    if entry["valid"] is False:
        assert r.get("op"), r
