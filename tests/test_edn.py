from jepsen_tpu import edn
from jepsen_tpu.edn import Keyword, Symbol, Tagged


def test_scalars():
    assert edn.loads("nil") is None
    assert edn.loads("true") is True
    assert edn.loads("false") is False
    assert edn.loads("42") == 42
    assert edn.loads("-7") == -7
    assert edn.loads("3.5") == 3.5
    assert edn.loads("1e3") == 1000.0
    assert edn.loads("123N") == 123
    assert edn.loads('"hi\\nthere"') == "hi\nthere"
    assert edn.loads(":foo") == Keyword("foo")
    assert edn.loads(":foo/bar").name == "foo/bar"
    assert edn.loads("sym") == Symbol("sym")
    assert edn.loads("\\a") == "a"
    assert edn.loads("\\newline") == "\n"


def test_collections():
    assert edn.loads("[1 2 3]") == [1, 2, 3]
    assert edn.loads("(1 2)") == [1, 2]
    assert edn.loads("#{1 2 3}") == frozenset({1, 2, 3})
    assert edn.loads("{:a 1, :b [2 3]}") == {Keyword("a"): 1, Keyword("b"): [2, 3]}
    # nested maps with collection keys
    assert edn.loads("{[1 2] 3}") == {(1, 2): 3}


def test_comments_and_discard():
    assert edn.loads("; comment\n42") == 42
    assert edn.loads("#_ignored 42") == 42
    assert edn.loads_all("1 2 ;x\n3") == [1, 2, 3]


def test_tagged():
    t = edn.loads('#inst "2017-09-01T00:00:00Z"')
    assert isinstance(t, Tagged)
    assert t.tag == "inst"


def test_reference_op_line():
    # exact shape from the reference README output (/root/reference/README.md:38-43)
    line = "{:process 85, :type :invoke, :f :read, :value nil, :index 110, :time 53268946400}"
    m = edn.loads(line)
    assert m[Keyword("process")] == 85
    assert m[Keyword("type")] == Keyword("invoke")
    assert m[Keyword("value")] is None
    assert m[Keyword("index")] == 110


def test_roundtrip():
    forms = [None, True, 42, "s", [1, [2]], {Keyword("k"): 1}, frozenset({1})]
    for f in forms:
        assert edn.loads(edn.dumps(f)) == f
