"""Tests for `jepsen probe` (jepsen_tpu.probe) — the r05 runbook's
hand-rolled device-health loop as a first-class subcommand.

The wedge and no-backend paths are driven by swapping the child code
(the same seam the runbook's real failures exercised: a child that
never answers vs a child that errors), so no TPU — and no actual
100-second wait — is needed. The healthy path runs the REAL child
pinned to the CPU backend."""

import io
import re
from unittest import mock

import pytest

from jepsen_tpu import probe

# one verdict line per attempt, PROBES_r05.log format: utc timestamp,
# "probe:", verdict text
_LINE = re.compile(r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z probe: ")


def _run(out, **kw):
    buf = io.StringIO()
    rc = probe.run_probe(out=buf, **kw)
    lines = buf.getvalue().splitlines()
    assert lines and all(_LINE.match(ln) for ln in lines), lines
    out.extend(lines)
    return rc


def test_probe_healthy_on_cpu(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    lines = []
    rc = _run(lines, timeout=120.0, retries=1)
    assert rc == probe.EXIT_HEALTHY == 0
    assert "HEALTHY" in lines[-1] and "jax.devices()" in lines[-1]
    assert "cpu" in lines[-1]


def test_probe_wedged_exhausts_retries_and_exits_1():
    with mock.patch.object(probe, "_CHILD_CODE",
                           "import time; time.sleep(3600)"):
        lines = []
        rc = _run(lines, timeout=0.8, retries=2)
    assert rc == probe.EXIT_WEDGED == 1
    hung = [ln for ln in lines if "(attempt " in ln]
    assert len(hung) == 2                       # one line per attempt
    assert "attempt 1/2" in hung[0] and "attempt 2/2" in hung[1]
    assert "WEDGED" in lines[-1]


def test_probe_no_backend_fails_fast_without_retries():
    """A child that RAN and failed is a different failure class:
    retrying cannot help, so the loop must stop after one attempt."""
    with mock.patch.object(probe, "_CHILD_CODE",
                           "raise RuntimeError('no plugin')") as _, \
            mock.patch.object(probe, "probe_once",
                              wraps=probe.probe_once) as spy:
        lines = []
        rc = _run(lines, timeout=30.0, retries=3)
    assert rc == probe.EXIT_NO_BACKEND == 2
    assert spy.call_count == 1
    assert "NO BACKEND" in lines[-1]
    assert "no plugin" in lines[-1]


def test_probe_recovers_mid_loop():
    """hung-then-healthy (the r05 03:46Z recovery): the loop keeps
    probing and the final verdict is HEALTHY / 0."""
    calls = {"n": 0}
    real = probe.probe_once

    def flaky(timeout):
        calls["n"] += 1
        if calls["n"] == 1:
            return {"status": "hung", "secs": timeout}
        return {"status": "healthy", "secs": 1.2,
                "platforms": ["tpu"], "n_devices": 4}

    with mock.patch.object(probe, "probe_once", flaky):
        lines = []
        rc = _run(lines, timeout=5.0, retries=3)
    assert rc == 0
    assert "hung past" in lines[0] and "HEALTHY" in lines[-1]
    assert "4 device(s)" in lines[-1]
    assert real is probe.probe_once is not flaky or True


def test_probe_cli_dispatch(monkeypatch):
    """`jepsen probe ...` forwards pre-parse like lint, honoring the
    probe module's own flags and exit contract."""
    from jepsen_tpu import cli

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert cli.main(["probe", "--timeout", "120", "--retries", "1"]) == 0
    # usage errors map to the CLI's bad-args convention, not exit 2
    # (which means no-backend here)
    assert probe.main(["--not-a-flag"]) == 254


def test_probe_json_healthy_schema(monkeypatch):
    """probe_json is the machine-readable side of the verdict lines —
    the SAME contract the circuit breaker's half-open recovery check
    consumes (resilience.breaker), pinned here."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    doc = probe.probe_json(timeout=120.0, retries=1)
    assert doc["verdict"] == "healthy" and doc["exit"] == 0
    assert doc["retries"] == 1 and doc["timeout"] == 120.0
    assert doc["elapsed_secs"] >= 0
    assert doc["attempts"][-1]["status"] == "healthy"
    assert "cpu" in doc["platforms"] and doc["n_devices"] >= 1


def test_probe_json_wedged_and_no_backend():
    with mock.patch.object(probe, "_CHILD_CODE",
                           "import time; time.sleep(3600)"):
        doc = probe.probe_json(timeout=0.5, retries=2)
    assert (doc["verdict"], doc["exit"]) == ("wedged", 1)
    assert [a["status"] for a in doc["attempts"]] == ["hung", "hung"]
    with mock.patch.object(probe, "_CHILD_CODE",
                           "raise RuntimeError('no plugin')"):
        doc = probe.probe_json(timeout=30.0, retries=3)
    assert (doc["verdict"], doc["exit"]) == ("no-backend", 2)
    assert len(doc["attempts"]) == 1      # fail-fast, no retries


def test_probe_json_cli(monkeypatch, capsys):
    """`jepsen probe --json`: exactly one JSON document on stdout,
    verdict lines on stderr, exit code unchanged."""
    import json

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    rc = probe.main(["--json", "--timeout", "120", "--retries", "1"])
    cap = capsys.readouterr()
    doc = json.loads(cap.out)
    assert rc == doc["exit"] == 0 and doc["verdict"] == "healthy"
    # the runbook's verdict-line format still flows, on stderr
    assert any(_LINE.match(ln) for ln in cap.err.splitlines())


@pytest.mark.parametrize("argv,expect", [
    (["--timeout", "7.5", "--retries", "2", "--interval", "1"],
     (7.5, 2, 1.0)),
    ([], (100.0, 3, 0.0)),
])
def test_probe_flag_parsing(argv, expect, monkeypatch):
    seen = {}

    def fake(timeout, retries, interval):
        seen.update(timeout=timeout, retries=retries, interval=interval)
        return 0

    monkeypatch.setattr(probe, "run_probe", fake)
    assert probe.main(argv) == 0
    assert (seen["timeout"], seen["retries"], seen["interval"]) == expect
