"""Lint fixture: obs telemetry calls inside traced code. NEVER
imported — parsed by tests/test_lint.py only (line numbers below are
asserted there; edit with care)."""

import jax
import jax.numpy as jnp
from jax import lax

from jepsen_tpu import obs
from jepsen_tpu.obs import span


def traced_helper(x):
    # reachable from the jitted root below
    obs.counter("engine.bad").inc()       # line 15: purity-obs-in-trace
    return x + 1


@jax.jit
def traced_root(x):
    with obs.span("engine.step"):         # line 21: purity-obs-in-trace
        y = traced_helper(x)
    with span("bare.import"):             # line 23: purity-obs-in-trace
        y = y * 2
    obs.registry().gauge("g").set(1)      # line 25: purity-obs-in-trace
    return y


def scan_user(xs):
    def body(carry, x):
        obs.histogram("h").observe(1.0)   # line 31: purity-obs-in-trace
        return carry + x, x

    return lax.scan(body, jnp.float32(0), xs)


def suppressed_trace_constant(x):
    @jax.jit
    def inner(y):  # jepsen-lint: disable=purity-obs-in-trace,recompile-closure-capture
        obs.counter("deliberate").inc()
        return y

    return inner(x)


def host_side_is_fine(model, xs):
    # NOT under any trace entry: spans/metrics here are the intended
    # pattern and must not flag
    with obs.span("engine.search", keys=len(xs)):
        obs.counter("engine.keys").inc(len(xs))
        return [model(x) for x in xs]
