"""Lint fixture: suppression syntax. NEVER imported — parsed by
tests/test_lint.py only (line numbers are asserted there)."""

import time

import jax
import jax.numpy as jnp
import numpy as np

# jepsen-lint: disable-file=purity-tracer-branch


@jax.jit
def traced(x):
    tbl = np.arange(4)  # jepsen-lint: disable=purity-numpy-call
    # jepsen-lint: disable=purity-host-call
    t = time.time()
    if jnp.any(x > 0):  # covered by the disable-file above
        x = x + 1
    return x + tbl.sum() + t


@jax.jit
def whole_fn(y):  # jepsen-lint: disable=purity-numpy-call
    # the def-line comment covers the entire body
    a = np.arange(3)
    b = np.zeros(3)
    return y + a + b


@jax.jit
def naked(x):
    t = time.time()  # jepsen-lint: disable
    return x + t     # the bare disable above is bad-suppression


@jax.jit
def unknown_rule(x):
    t = time.time()  # jepsen-lint: disable=not-a-rule
    return x + t


# own-line comment above a DECORATED def lands on the decorator line —
# it must still cover the function body
# jepsen-lint: disable=purity-host-call
@jax.jit
def decorated_covered(x):
    t = time.time()
    return x + t


import functools  # noqa: E402


# device pragma above a decorated def must still register the root
# jepsen-lint: device
@functools.lru_cache(None)
def pragma_decorated(x):
    t = time.time()
    return x + t


@jax.jit
def gap_suppressed(x):
    # jepsen-lint: disable=purity-numpy-call
    # an explanatory comment (or blank line) between the directive and
    # the statement must not void the suppression

    tbl = np.arange(5)
    return x + tbl
