"""Clean twin of locks_viol.py: every shape the lock-discipline rules
must stay silent on.

  * both nesting sites acquire A then B — consistent order, no cycle
  * file I/O strictly outside the lock
  * `wait()` on the condition the function HOLDS (the sanctioned
    idiom: wait releases it)
  * a field whose every write holds the same lock (fully guarded)
  * explicit acquire()/release() in the same A-then-B order
"""
import threading


class Clean:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._cond = threading.Condition()
        self.count = 0
        self.ok = False
        threading.Thread(target=self._worker).start()

    def ab1(self):
        with self._a:
            with self._b:
                self.count = 1

    def ab2(self):
        with self._a:
            with self._b:
                self.count = 2

    def _worker(self):
        with self._a:
            with self._b:
                self.count = 3

    def waiter(self):
        with self._cond:
            while not self.ok:
                self._cond.wait(timeout=0.1)

    def dump(self):
        with self._a:
            items = list(range(3))
        with open("/tmp/lint_fixture_ok", "w") as fh:
            fh.write(str(items))

    def explicit(self):
        self._a.acquire()
        with self._b:
            pass
        self._a.release()
