"""Lint fixture: device-purity violations. NEVER imported — parsed by
tests/test_lint.py only (line numbers below are asserted there; edit
with care)."""

import os
import random
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def helper(x):
    # reachable from the jitted root via the call below
    t = time.time()                       # line 18: purity-host-call
    return x + t


@jax.jit
def traced_root(x):
    y = helper(x)
    noise = random.random()               # line 25: purity-host-call
    flag = os.environ.get("SOME_VAR")     # line 26: purity-host-call
    tbl = np.arange(8)                    # line 27: purity-numpy-call
    if jnp.any(y > 0):                    # line 28: purity-tracer-branch
        y = y + 1
    while jnp.sum(y) > 0:                 # line 30: purity-tracer-branch
        y = y - 1
    ok = bool(jnp.all(y == 0))            # line 32: purity-tracer-branch
    return y, noise, flag, tbl, ok


def scan_user(xs):
    def body(carry, x):
        with open("/tmp/leak") as fh:     # line 38: purity-host-call
            _ = fh
        print("tracing", x)               # line 40: purity-host-call
        return carry + x, x

    return lax.scan(body, jnp.float32(0), xs)


def host_side_is_fine():
    # NOT reachable from any trace entry: none of these may be flagged
    t = time.time()
    r = random.random()
    a = np.arange(4)
    return t, r, a
