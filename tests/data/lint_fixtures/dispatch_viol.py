"""Fixture: concurrency-unsupervised-dispatch violations.

A device-dispatch entry point called outside a supervisor.dispatch
thunk is a dispatch the resilience layer cannot see. Lines are pinned
by tests/test_lint.py — keep the layout stable.
"""
from jepsen_tpu.resilience import supervisor as sup


def _check_device(xs, state0):          # stand-in for the jitted entry
    return xs, state0


def _check_bitdense_batch(xs, state0):
    return xs, state0


def bad_direct_call(xs, state0):
    # VIOLATION (next line): bare dispatch, no supervision
    return _check_device(xs, state0)


def bad_via_helper(xs, state0):
    # VIOLATION (next line): also bare — the helper is not a
    # supervised root either
    return _check_bitdense_batch(xs, state0)


def good_lambda(xs, state0):
    return sup.dispatch("search", lambda: _check_device(xs, state0))


def good_named_thunk(xs, state0):
    def _run():
        return _check_device(xs, state0)
    return sup.dispatch("search", _run, backend="cpu")


def good_reachable_helper(xs, state0):
    def _materialize():
        return list(_helper(xs, state0))
    return sup.dispatch("dispatch", _materialize)


def _helper(xs, state0):
    # reachable FROM a supervised thunk: not a violation
    return _check_bitdense_batch(xs, state0)


def suppressed_call(xs, state0):
    # deliberate bare-program benchmark, rule-named escape
    return _check_device(xs, state0)  # jepsen-lint: disable=concurrency-unsupervised-dispatch
