"""Lint fixture: recompilation hazards. NEVER imported — parsed by
tests/test_lint.py only (line numbers are asserted there)."""

import functools

import jax
import jax.numpy as jnp

CFG = {"a": 1, "b": 2}


def per_call_jit(x):
    # a fresh wrapper every call: the compile cache never hits
    f = jax.jit(lambda a: a * 2)          # line 14: recompile-closure-capture
    return f(x)


def scalar_capture(scale):
    def inner(a):
        return a * scale

    return jax.jit(inner)(jnp.ones(3))    # line 22: recompile-closure-capture


@functools.partial(jax.jit, static_argnames=tuple(CFG.keys()))  # line 25
def dict_order_static(x, a=1, b=2):
    return x + a + b


good = jax.jit(lambda a: a + 1, static_argnames=("n",))  # literal: clean
