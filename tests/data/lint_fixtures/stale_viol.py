"""Fixture: stale vs used suppressions (rule lint-stale-suppression).

The env-flag suppression below is USED (the rule really fires there,
so the directive earns its keep); the purity-numpy-call one covers a
line the rule cannot fire on — the stale-suppression pass must flag
exactly that one, anchored at the directive's own line.
"""
import os


def read_flag():
    return os.environ.get("JEPSEN_TPU_DEMO")  # jepsen-lint: disable=env-flag-accessor


def harmless():
    x = 1 + 1   # jepsen-lint: disable=purity-numpy-call
    return x
