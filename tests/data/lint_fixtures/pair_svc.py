"""Cross-module pair fixture, side A: calls into the partner module
(pair_wal.py) while holding its own lock. Clean on its own — the
cycle only closes across the pair (locks.pair_findings)."""
import threading


class Service:
    def __init__(self, wal):
        self._lock = threading.Lock()
        self._wal = wal

    def publish(self, rec):
        with self._lock:
            self._wal.append(rec)
