"""Drift-fixture mints: the static mint shapes the metric gate
collects. `app.orphan` has no doc row (drift); everything else is
covered by docs/obs.md."""
import obs


def emit(tenant, key, n):
    obs.counter("app.hits").inc()
    obs.counter("app.misses").inc(n)
    obs.gauge("app.depth").set(n)
    obs.histogram(obs.labeled("app.latency", tenant=tenant)).observe(n)
    obs.counter(f"app.dyn.{key}").inc()
    obs.counter("app.orphan").inc()
    plain_counter("app.not_a_metric")   # wrong receiver: not a mint
    obs.span("app.run")                 # spans are not metrics


def plain_counter(name):
    return name
