"""Drift-fixture registry: the comment-table shape the flag gate
parses. ALPHA is documented in docs/flags.md; BETA is not (drift)."""

# Registered flags (one row per flag, same grammar as the real
# jepsen_tpu/envflags.py table):
#
#   JEPSEN_TPU_ALPHA         env_int     mod — a documented flag
#   JEPSEN_TPU_BETA          env_bool    mod — an UNdocumented flag
