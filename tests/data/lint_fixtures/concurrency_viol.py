"""Lint fixture: concurrency + env-flag-hygiene violations. NEVER
imported — parsed by tests/test_lint.py only (line numbers are
asserted there)."""

import os
import threading


class Shared:
    def __init__(self):
        self.value = 0
        self.lock = threading.Lock()


def spawn_unlocked(shared):
    def run():
        shared.value = 42           # line 17: concurrency-unlocked-shared-write

    t = threading.Thread(target=run)
    t.start()
    return t


def spawn_locked(shared):
    def run():
        with shared.lock:
            shared.value = 42       # locked: clean


    t = threading.Thread(target=run)
    t.start()
    return t


COUNTER = 0


def spawn_global():
    def bump():
        global COUNTER
        COUNTER = COUNTER + 1       # line 41: concurrency-unlocked-shared-write

    threading.Thread(target=bump).start()


def read_flags():
    # the exact JEPSEN_TPU_PALLAS regression the linter must catch when
    # reintroduced (bitdense read this raw before the accessor existed)
    a = os.environ.get("JEPSEN_TPU_PALLAS")      # line 49: env-flag-accessor
    b = os.getenv("JEPSEN_TPU_CLOSURE")          # line 50: env-flag-accessor
    c = os.environ["JEPSEN_TPU_BUCKET"]          # line 51: env-flag-accessor
    d = os.environ.get("NOT_OURS")               # foreign namespace: clean
    return a, b, c, d


class Box:
    latest = 0


SHARED_BOX = Box()


class Poller:
    """Bound-method thread target (the membership-nemesis shape):
    the method must be analyzed too, not just Name/Lambda targets."""

    def start(self):
        threading.Thread(target=self._poll, daemon=True).start()

    def _poll(self):
        SHARED_BOX.latest = 1  # line 71: concurrency-unlocked-shared-write
