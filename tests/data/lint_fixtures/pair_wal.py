"""Cross-module pair fixture, side B: calls BACK into side A
(pair_svc.py) under its own lock — the opposite acquisition order, so
the lock-order cycle exists only in the combined graph."""
import threading


class Wal:
    def __init__(self, svc):
        self._mu = threading.Lock()
        self._svc = svc

    def append(self, rec):
        with self._mu:
            self._svc.publish(rec)
