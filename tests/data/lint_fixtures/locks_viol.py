"""Violation fixture: the lock-discipline rules (analysis/locks.py).

NOT imported by anything — parsed by tests/test_lint.py, which pins
these anchors:

  concurrency-lock-order            line 29 (the A->B / B->A cycle,
                                    anchored at the first edge site)
  concurrency-blocking-under-lock   line 49 (flight dump under the
                                    condition — the PR-8 regression
                                    shape), 54, 55, 56 (open/write/
                                    foreign wait), 61 (sleep),
                                    68 (inlined one level from
                                    `outer`)
  concurrency-unguarded-field       line 96 (worker-thread RMW of a
                                    field 9/10 guarded — the PR-11
                                    blocking-freeze regression shape)
"""
import threading
import time


class Cycle:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                x = 1
        return x

    def ba(self):
        with self._b:
            with self._a:
                x = 2
        return x


class Dumper:
    def __init__(self, obs):
        self._cond = threading.Condition()
        self._lock = threading.Lock()
        self._other = threading.Condition()
        self._obs = obs

    def crash_dump(self):
        with self._cond:
            self._obs.flight_dump("postmortem", context={})
            self._cond.wait(timeout=0.1)    # held cond: sanctioned

    def freeze(self):
        with self._lock:
            fh = open("/tmp/lint_fixture", "w")
            fh.write("x")
            self._other.wait()              # foreign condition
        fh.close()

    def nap(self):
        with self._lock:
            time.sleep(0.1)

    def outer(self):
        with self._lock:
            self._io()

    def _io(self):
        open("/tmp/lint_fixture2", "w").close()


class Tally:
    def __init__(self):
        self._lock = threading.Lock()
        self.frozen = 0
        threading.Thread(target=self._worker).start()

    def bump(self, n):
        with self._lock:
            self.frozen = n
            self.frozen = n + 1
            self.frozen = n + 2

    def set_many(self):
        with self._lock:
            self.frozen = 3
            self.frozen = 4
            self.frozen = 5

    def reset(self):
        with self._lock:
            self.frozen = -1
            self.frozen = -2
            self.frozen = -3

    def _worker(self):
        self.frozen += 1
