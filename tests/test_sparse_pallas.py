"""Interpret-mode parity suite for the fused sparse frontier kernel
(JEPSEN_TPU_SPARSE_PALLAS, parallel.sparse_kernels): the hash dedupe
path through one VMEM-resident pallas_call per event closure
(single-device) / per insert (sharded) must land verdict, failing op +
event, max-frontier, capacity, explored, AND configs-stepped identical
to both the sort strategy and the XLA hash strategy — across the
sparse families, clean + corrupted, single-key / batch / pipelined /
sharded / resumable — plus the probe-overflow -> capacity-escalation
contract, the VMEM shape-gate fallback note, and the
JEPSEN_TPU_SPARSE_PALLAS / JEPSEN_TPU_PROBE_LIMIT flag plumbing. The
randomized arm (vs the WGL oracle) rides the fuzz tier
(test_fuzz_differential's sparse-hash-pallas engine entry)."""

import os
import unittest.mock as mock

import pytest

from jepsen_tpu.histories import (adversarial_register_history,
                                  corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import (encode as enc_mod, engine,
                                 sparse_kernels)

# Everything order-independent must MATCH across the three
# implementations (sort / XLA hash / pallas hash); only frontier ROW
# ORDER may differ — and between the two hash forms not even that:
# the kernel body is the same _hash_event_closure trace.
PIN = ("valid?", "op", "fail-event", "max-frontier", "capacity",
       "explored")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _triple(e, capacity=128, max_capacity=4096):
    """sort vs XLA hash vs pallas hash on one encoded history."""
    rs = engine.check_encoded(e, capacity=capacity,
                              max_capacity=max_capacity, dedupe="sort")
    rh = engine.check_encoded(e, capacity=capacity,
                              max_capacity=max_capacity, dedupe="hash")
    rp = engine.check_encoded(e, capacity=capacity,
                              max_capacity=max_capacity, dedupe="hash",
                              sparse_pallas=True)
    assert _pin(rs) == _pin(rh) == _pin(rp), (rs, rh, rp)
    if rs["valid?"] != "unknown":
        # the two hash forms share one trace: the advisory counter is
        # bit-identical, not merely <= the sort path's
        assert rp["configs-stepped"] == rh["configs-stepped"], (rh, rp)
        assert rp["closure"] == "pallas", rp
        assert "closure" not in rh, rh     # flag off => schema unchanged
    return rs, rh, rp


# same generators (and therefore the same compiled shapes) as
# tests/test_dedupe.py's deterministic pin — the sort/XLA-hash programs
# are shared with that module's jit cache; only the kernel variant
# compiles fresh here
FAMILIES = [
    ("cas-register", CASRegister,
     lambda: rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31)),
    ("gset", GSet,
     lambda: rand_gset_history(n_ops=36, n_processes=4, n_elements=9,
                               crash_p=0.06, seed=33)),
    ("uqueue", UnorderedQueue,
     lambda: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                crash_p=0.06, seed=34)),
    ("fifo", FIFOQueue,
     lambda: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                               crash_p=0.05, seed=35)),
]


@pytest.mark.parametrize("name,Model,gen", FAMILIES,
                         ids=[c[0] for c in FAMILIES])
def test_kernel_parity_clean_and_corrupted(name, Model, gen):
    h = gen()
    for variant in (h, corrupt_history(h, seed=7, n_corruptions=2)):
        try:
            e = enc_mod.encode(Model(), variant)
        except enc_mod.EncodeError:
            continue  # family/shape not device-encodable: nothing to pin
        _triple(e)


def test_kernel_parity_mutex_invalid():
    h = History.wrap([
        invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
        invoke_op(1, "acquire", None), ok_op(1, "acquire", None),
    ]).index()
    e = enc_mod.encode(Mutex(), h)
    rs, _, _ = _triple(e, capacity=64, max_capacity=256)
    assert rs["valid?"] is False


def test_kernel_parity_adversarial_delta_counter():
    """The acceptance shape: the kernel must report the same strict
    configs-stepped reduction vs sort that the XLA hash path does."""
    h = adversarial_register_history(n_ops=120, k_crashed=6, seed=7)
    e = enc_mod.encode(CASRegister(), h)
    rs, rh, rp = _triple(e, capacity=1024, max_capacity=4096)
    assert rs["valid?"] is True
    assert rp["configs-stepped"] < rs["configs-stepped"], (rs, rp)


def test_probe_overflow_escalates_capacity_not_verdict():
    """probe_limit=1 makes every collision a probe exhaustion INSIDE
    the kernel — it must ride the capacity-escalation retry (bigger
    table, lower load factor) to the sort verdict, never mis-verdict
    or drop a config."""
    h = rand_register_history(n_ops=50, n_processes=5, n_values=4,
                              crash_p=0.05, fail_p=0.05, seed=11)
    e = enc_mod.encode(CASRegister(), h)
    ref = engine.check_encoded(e, capacity=64, dedupe="sort")
    r1 = engine.check_encoded(e, capacity=64, max_capacity=1 << 14,
                              dedupe="hash", probe_limit=1,
                              sparse_pallas=True)
    assert r1["valid?"] == ref["valid?"]
    assert r1.get("op") == ref.get("op")
    assert r1["capacity"] >= ref["capacity"]


def test_vmem_shape_gate_goes_tiled_not_wholesale():
    """A capacity past the whole-event fusion gate no longer degrades
    wholesale: the closure runs with the table streamed through VMEM
    tiles (closure="pallas-tiled", sparse_kernels.tiled_insert_call)
    and stays bit-identical to the XLA hash."""
    h = rand_register_history(n_ops=40, n_processes=5, n_values=3,
                              crash_p=0.06, fail_p=0.08, seed=31)
    e = enc_mod.encode(CASRegister(), h)
    big = 16384
    assert not sparse_kernels.supported(big, e.slot_f.shape[1])
    assert sparse_kernels.tiled_plan(big, e.slot_f.shape[1]) is not None
    ref = engine.check_encoded(e, capacity=big, dedupe="hash")
    r = engine.check_encoded(e, capacity=big, dedupe="hash",
                             sparse_pallas=True)
    assert r["closure"] == "pallas-tiled"
    assert r["valid?"] == ref["valid?"]
    assert r["configs-stepped"] == ref["configs-stepped"]
    # the flag-off reference is tag-free: byte-identical schema
    assert "closure" not in ref and "closure-note" not in ref


def test_vmem_budget_too_small_falls_back_with_note():
    """Only a budget too small even for the tiled planner degrades to
    the XLA hash closure, with the note — the bitdense mesh-fallback
    precedent: the requested-kernel path degrades, it never errors.
    JEPSEN_TPU_VMEM_BUDGET is the per-generation re-gate knob."""
    h = rand_register_history(n_ops=40, n_processes=5, n_values=3,
                              crash_p=0.06, fail_p=0.08, seed=31)
    e = enc_mod.encode(CASRegister(), h)
    big = 16384
    with mock.patch.dict(os.environ,
                         {"JEPSEN_TPU_VMEM_BUDGET": str(1 << 16)}):
        assert sparse_kernels.vmem_budget() == 1 << 16
        assert sparse_kernels.tiled_plan(big, e.slot_f.shape[1]) is None
        ref = engine.check_encoded(e, capacity=big, dedupe="hash")
        r = engine.check_encoded(e, capacity=big, dedupe="hash",
                                 sparse_pallas=True)
    assert r["closure"] == "xla-hash"
    assert "VMEM budget" in r["closure-note"]
    assert r["valid?"] == ref["valid?"]
    assert "closure" not in ref and "closure-note" not in ref


def test_supported_budget_math():
    """Pin the WIDTH-AWARE gate accounting: bytes_per_row(lanes) =
    12*lanes + 12 of probe state per candidate row (M = N*C) plus the
    frontier tile, against the (env-overridable) VMEM budget — 48 B at
    the unpacked 3-lane triple (the historical constant), 24 B at one
    packed lane."""
    assert sparse_kernels.bytes_per_row(3) == 48
    assert sparse_kernels.bytes_per_row(2) == 36
    assert sparse_kernels.bytes_per_row(1) == 24
    assert sparse_kernels.insert_supported(1024, 1024)
    assert sparse_kernels.supported(1024, 14)          # bench-ish shape
    assert not sparse_kernels.supported(16384, 7)
    # packing admits shapes the unpacked layout cannot fit
    assert sparse_kernels.supported(16384, 7, lanes=1)
    limit = sparse_kernels.VMEM_BUDGET // 48
    assert sparse_kernels.insert_supported(limit - 64, 64)
    assert not sparse_kernels.insert_supported(limit, 64)
    # the env knob re-gates without a code edit; below-minimum raises
    from jepsen_tpu.envflags import EnvFlagError
    with mock.patch.dict(os.environ,
                         {"JEPSEN_TPU_VMEM_BUDGET": str(8 << 20)}):
        assert sparse_kernels.supported(16384, 7)
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_VMEM_BUDGET": "17"}), \
            pytest.raises(EnvFlagError, match="VMEM_BUDGET"):
        sparse_kernels.vmem_budget()


def test_env_flag_resolution_and_validation():
    from jepsen_tpu.envflags import EnvFlagError
    h = rand_register_history(n_ops=24, n_processes=3, crash_p=0.0,
                              seed=5)
    e = enc_mod.encode(CASRegister(), h)
    # default: off, no tags
    r = engine.check_encoded(e, capacity=64, dedupe="hash")
    assert "closure" not in r
    # JEPSEN_TPU_SPARSE_PALLAS=1 forces the kernel (interpret on CPU)
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_SPARSE_PALLAS": "1"}):
        r = engine.check_encoded(e, capacity=64, dedupe="hash")
    assert r["closure"] == "pallas" and r["valid?"] is True
    # strict tri-state: anything else raises at the read site
    with mock.patch.dict(os.environ,
                         {"JEPSEN_TPU_SPARSE_PALLAS": "yes"}), \
            pytest.raises(EnvFlagError, match="SPARSE_PALLAS"):
        engine.check_encoded(e, capacity=64, dedupe="hash")
    # the kernel is the hash path's form: requesting it under sort is
    # a contradiction, loudly rejected (not silently ignored)
    with pytest.raises(ValueError, match="dedupe='hash'"):
        engine.check_encoded(e, capacity=64, dedupe="sort",
                             sparse_pallas=True)


def test_probe_limit_flag_one_knob_for_both_paths():
    from jepsen_tpu.envflags import EnvFlagError
    # explicit argument wins; unset flag -> default 32
    assert engine._resolve_probe_limit(7) == 7
    assert engine._resolve_probe_limit(0) == 32
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_PROBE_LIMIT": "3"}):
        assert engine._resolve_probe_limit(0) == 3
    for bad in ("0", "-2", "many"):
        with mock.patch.dict(os.environ,
                             {"JEPSEN_TPU_PROBE_LIMIT": bad}), \
                pytest.raises(EnvFlagError, match="PROBE_LIMIT"):
            engine._resolve_probe_limit(0)
    # the flag reaches BOTH hash implementations: a 1-probe limit
    # forces the same escalated capacity out of the XLA and the kernel
    # path on a collision-heavy history
    h = rand_register_history(n_ops=50, n_processes=5, n_values=4,
                              crash_p=0.05, fail_p=0.05, seed=11)
    e = enc_mod.encode(CASRegister(), h)
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_PROBE_LIMIT": "1"}):
        rx = engine.check_encoded(e, capacity=64, max_capacity=1 << 14,
                                  dedupe="hash")
        rp = engine.check_encoded(e, capacity=64, max_capacity=1 << 14,
                                  dedupe="hash", sparse_pallas=True)
    ref = engine.check_encoded(e, capacity=64, max_capacity=1 << 14,
                               dedupe="hash")
    assert rx["capacity"] == rp["capacity"] >= ref["capacity"]
    assert rx["valid?"] == rp["valid?"] == ref["valid?"]


def test_batch_and_pipeline_thread_the_kernel():
    """check_batch(sparse_pallas=True) must reach the sparse buckets in
    both executors with results identical to the XLA hash path (modulo
    the closure tag); bitdense buckets are untouched by the flag."""
    regs = [rand_register_history(n_ops=24, n_processes=3, crash_p=0.02,
                                  seed=600 + s) for s in range(3)]
    fifo = rand_fifo_history(n_ops=36, n_processes=6, n_values=3,
                             crash_p=0.15, seed=5)

    rs = engine.check_batch(CASRegister(), regs, capacity=64,
                            max_capacity=2048, dedupe="hash",
                            sparse_pallas=True)
    assert all(r["dedupe"] == "dense" for r in rs), rs

    pre = [enc_mod.encode(FIFOQueue(), fifo)]
    r_hash = engine._check_batch_sparse(FIFOQueue(), pre, 128, 2048,
                                        dedupe="hash")[0]
    r_pal = engine._check_batch_sparse(FIFOQueue(), pre, 128, 2048,
                                       dedupe="hash",
                                       sparse_pallas=True)[0]
    strip = lambda r: {k: v for k, v in r.items()  # noqa: E731
                       if k != "closure"}
    assert strip(r_pal) == strip(r_hash), (r_pal, r_hash)
    assert r_pal["closure"] == "pallas" and "closure" not in r_hash

    stats = {}
    rs_p = engine.check_batch(FIFOQueue(), [fifo], capacity=128,
                              max_capacity=2048, pipeline=True,
                              cache=False, pipeline_stats=stats,
                              dedupe="hash", sparse_pallas=True)
    assert stats["dedupe"] == "hash"
    assert rs_p[0] == r_pal, (rs_p[0], r_pal)


def test_resumable_kernel_matches_oneshot():
    h = rand_register_history(n_ops=120, n_processes=6, n_values=4,
                              crash_p=0.01, fail_p=0.05, busy=0.7,
                              seed=10)
    e = enc_mod.encode(CASRegister(), h)
    ref = engine.check_encoded(e, capacity=256, dedupe="hash")
    res = engine.check_encoded_resumable(e, capacity=256,
                                         checkpoint_every=16,
                                         dedupe="hash",
                                         sparse_pallas=True)
    assert res["valid?"] == ref["valid?"]
    assert res["max-frontier"] == ref["max-frontier"]
    assert res["configs-stepped"] == ref["configs-stepped"]
    assert res["closure"] == "pallas"


def test_sharded_1d_insert_kernel_parity():
    """The sharded engine's per-device owned tables through the fused
    insert kernel (1-D mesh): verdict/max-frontier/configs-stepped
    identical to the XLA hash AND the sort strategies."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from jepsen_tpu.parallel import sharded

    h = rand_register_history(n_ops=60, n_processes=6, n_values=4,
                              crash_p=0.02, fail_p=0.05, seed=10)
    e = enc_mod.encode(CASRegister(), h)
    mesh = Mesh(np.array(jax.devices()), ("frontier",))
    r_sort = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                           dedupe="sort")
    r_hash = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                           dedupe="hash")
    r_pal = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                          dedupe="hash",
                                          sparse_pallas=True)
    for k in ("valid?", "op", "fail-event", "max-frontier", "capacity"):
        assert r_sort.get(k) == r_hash.get(k) == r_pal.get(k), \
            (k, r_sort, r_hash, r_pal)
    assert r_pal["configs-stepped"] == r_hash["configs-stepped"]
    assert r_pal["closure"] == "pallas" and "closure" not in r_hash


@pytest.mark.slow
def test_sharded_2d_insert_kernel_parity():
    """Hierarchical (slice x chip) exchange with the insert kernel —
    slow tier: a fresh 2-D shard_map program is a 10s-class compile on
    the CPU backend, and the 1-D case already pins the insert fusion;
    this adds only the two-stage-routing composition."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from jepsen_tpu.parallel import sharded

    h = rand_register_history(n_ops=60, n_processes=6, n_values=4,
                              crash_p=0.02, fail_p=0.05, seed=10)
    e = enc_mod.encode(CASRegister(), h)
    mesh2d = Mesh(np.array(jax.devices()).reshape(2, 4),
                  ("slice", "chip"))
    r_hash = sharded.check_encoded_sharded(e, mesh2d, capacity=512,
                                           dedupe="hash")
    r_pal = sharded.check_encoded_sharded(e, mesh2d, capacity=512,
                                          dedupe="hash",
                                          sparse_pallas=True)
    for k in ("valid?", "op", "fail-event", "max-frontier", "capacity",
              "configs-stepped", "mesh"):
        assert r_hash.get(k) == r_pal.get(k), (k, r_hash, r_pal)
    assert r_pal["closure"] == "pallas"
