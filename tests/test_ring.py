"""Replica scale-out suite (ISSUE 12 acceptance): consistent-hash
ring math, WAL-segment + freeze/thaw key migration, crash re-homing
with bit-identical verdicts — including a REAL kill -9 of a replica
subprocess mid-stream.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.history import History
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, engine, programs
from jepsen_tpu.serve import CheckerService, DeltaWAL
from jepsen_tpu.serve import ring as ring_mod

PIN = ("valid?", "op", "fail-event", "max-frontier", "configs-stepped")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _oneshot(ops, capacity=128):
    e = enc_mod.encode(CASRegister(), History.wrap(list(ops)))
    return engine.check_encoded(e, capacity=capacity, dedupe="sort")


def _history(seed=2, corrupt=True):
    h = rand_register_history(n_ops=24, n_processes=4, n_values=3,
                              crash_p=0.05, seed=seed)
    if corrupt:
        h = corrupt_history(h, seed=1, n_corruptions=2)
    return list(h)


# ------------------------------------------------------------- ring


def test_hash_ring_deterministic_and_stable():
    r1 = ring_mod.HashRing(["a", "b", "c"])
    r2 = ring_mod.HashRing(["c", "a", "b"])   # order-independent
    keys = [("reg", i) for i in range(200)]
    owners = {k: r1.owner(k) for k in keys}
    assert {r2.owner(k) for k in keys} == set(owners.values())
    assert all(r2.owner(k) == o for k, o in owners.items())
    # every node owns a nontrivial share (vnodes spread the arcs)
    counts = {n: sum(1 for o in owners.values() if o == n)
              for n in "abc"}
    assert all(c > 20 for c in counts.values()), counts
    # consistency: removing b moves ONLY b's keys
    r1.remove("b")
    for k, o in owners.items():
        if o != "b":
            assert r1.owner(k) == o
        else:
            assert r1.owner(k) in ("a", "c")
    # adding b back restores the original assignment exactly
    r1.add("b")
    assert all(r1.owner(k) == o for k, o in owners.items())


def test_hash_ring_assignments_and_empty():
    r = ring_mod.HashRing(["x", "y"])
    plan = r.assignments([("reg", i) for i in range(20)])
    assert sum(len(v) for v in plan.values()) == 20
    with pytest.raises(ValueError, match="no nodes"):
        ring_mod.HashRing([]).owner("k")


# ----------------------------------------------- in-process rehoming


def test_router_crash_rehome_bit_identical(tmp_path):
    """Crash path: one replica dies (close without drain — the
    in-process stand-in for a kill; the subprocess test below does it
    with a real SIGKILL), survivors adopt its WAL segments +
    checkpoint, and every migrated key's verdict is bit-identical to
    an unmigrated one-shot check."""
    m = CASRegister()
    h = _history()
    ref = _oneshot(h)
    dirs = {n: str(tmp_path / n) for n in ("r1", "r2")}
    svcs = {n: CheckerService(m, wal_dir=d, capacity=128)
            for n, d in dirs.items()}
    router = ring_mod.Router(svcs, dirs)
    key = "mig-key"
    dead = router.owner(key)
    survivor = next(n for n in dirs if n != dead)
    try:
        r = router.submit(key, h[:12], wait=True, timeout=120)
        assert "valid?" in r
        # second delta ACKED but possibly unapplied at the crash: the
        # WAL has it, so the survivor must land it too
        assert router.submit(key, h[12:], timeout=60)["accepted"]
        svcs[dead].close(drain=False)
        plan = router.rehome(dead)
        assert plan == {survivor: [key]}
        rr = router.result(key, timeout=120)
        assert _pin(rr) == _pin(ref) and rr["seq"] == 2
        # the re-homed key keeps serving: a replayed delta dedupes by
        # seq exactly like it would on the original replica
        assert router.submit(key, h[12:], seq=2)["duplicate"]
        f = router.finalize(key, timeout=120)
        assert _pin(f) == _pin(ref)
    finally:
        for s in router.services.values():
            s.close()


def test_router_graceful_migration_freeze_thaw(tmp_path):
    """Graceful path: freeze_key persists the live frontier, the
    transfer ships checkpoint + WAL segments, and the destination
    thaws instead of re-scanning (pinned via the checkpoint meta
    landing on the destination and verdict parity)."""
    m = CASRegister()
    h = _history(seed=5, corrupt=False)
    ref = _oneshot(h)
    dirs = {n: str(tmp_path / n) for n in ("ra", "rb")}
    svcs = {n: CheckerService(m, wal_dir=d, capacity=128)
            for n, d in dirs.items()}
    router = ring_mod.Router(svcs, dirs)
    key = "gkey"
    src = router.owner(key)
    dst = next(n for n in dirs if n != src)
    try:
        router.submit(key, h, wait=True, timeout=120)
        r = router.migrate_key(key, dst)
        assert r["from"] == src and r["to"] == dst
        assert r["segments"] >= 1 and r["checkpoint"] is True
        # the frozen checkpoint pair really landed on the destination
        cps = os.listdir(os.path.join(dirs[dst], "checkpoints"))
        assert any(n.endswith(".json") for n in cps)
        rr = svcs[dst].result(key, timeout=120)
        assert _pin(rr) == _pin(ref) and rr["seq"] == 1
    finally:
        for s in svcs.values():
            s.close()


# ------------------------------------------------ cross-process kill


_CHILD = r"""
import json, os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from jepsen_tpu.models import CASRegister
from jepsen_tpu.serve import CheckerService
from jepsen_tpu.serve.ingress import DeltaIngress
svc = CheckerService(CASRegister(), wal_dir=sys.argv[1], capacity=128,
                     evict_idle_secs=0.2)
ing = DeltaIngress(svc, port=0).start()
print(json.dumps({"port": ing.port}), flush=True)
while True:
    time.sleep(1)
"""


def _http_deltas(port, reqs, timeout=180):
    import urllib.request
    body = "".join(json.dumps(r) + "\n" for r in reqs).encode()
    req = urllib.request.Request(f"http://127.0.0.1:{port}/v1/deltas",
                                 data=body)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return [json.loads(ln) for ln in
                resp.read().decode().splitlines()]


def test_kill9_replica_rehomes_keys_bit_identical(tmp_path,
                                                  monkeypatch):
    """THE acceptance pin: kill -9 a replica process mid-stream; its
    keys re-home onto a survivor via WAL-segment transfer + the
    frozen checkpoint (eviction froze the key before the kill, so the
    handoff exercises freeze/thaw, not just replay), and the migrated
    key's final verdict is bit-identical to an unmigrated one-shot
    check of the same ops.

    Compile economics rides the same kill (ISSUE 17): the replica and
    the survivor share one JEPSEN_TPU_COMPILE_CACHE dir (+ canonical
    shapes, the run-it-fleet-wide posture docs/streaming.md requires);
    the frozen key's program manifest travels with the WAL segments,
    adoption pre-warms from it, and the survivor's first POST-adoption
    delta is served with zero fresh compiles — the registry ledger
    proves the warm handoff, the pin proves it changed nothing."""
    m = CASRegister()
    # seed=2: the stream's slot concurrency C is already at its final
    # width by the first delta, so the canonical-shapes contract can
    # hold exactly — the adopter's chunk shapes all match programs the
    # dead replica compiled (canon quantizes event ROWS; a delta that
    # widens C legitimately compiles fresh — the docs/streaming.md
    # canonical-shapes caveat)
    h = _history(seed=2)
    # ref computed BEFORE arming the flags: the baseline stays
    # flag-off, and the test-process registry ledger starts at zero —
    # every compile it ever counts is the survivor's own
    ref = _oneshot(h)
    cache_dir = str(tmp_path / "progcache")
    monkeypatch.setenv("JEPSEN_TPU_COMPILE_CACHE", cache_dir)
    monkeypatch.setenv("JEPSEN_TPU_CANON_SHAPES", "1")
    programs.reset()
    dead_dir = str(tmp_path / "dead")
    live_dir = str(tmp_path / "live")
    script = tmp_path / "replica.py"
    script.write_text(_CHILD)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               JEPSEN_TPU_COMPILE_CACHE=cache_dir,
               JEPSEN_TPU_CANON_SHAPES="1",
               PYTHONPATH=repo + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("JEPSEN_TPU_FAULTS", None)
    proc = subprocess.Popen([sys.executable, str(script), dead_dir],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, env=env,
                            cwd=repo)
    survivor = None
    try:
        line = proc.stdout.readline().decode()
        assert line, "replica subprocess produced no port line"
        port = json.loads(line)["port"]
        key = "k9"
        outs = _http_deltas(port, [{"key": key,
                                    "ops": [dict(o) for o in h[:12]],
                                    "wait": True, "timeout": 150}])
        assert outs[0].get("valid?") is not None
        # let the idle key evict: the frontier freezes to the
        # checkpoint store, which is exactly what the handoff ships
        deadline = time.time() + 20
        cps_dir = os.path.join(dead_dir, "checkpoints")
        while time.time() < deadline:
            if os.path.isdir(cps_dir) and any(
                    n.endswith(".json") for n in os.listdir(cps_dir)):
                break
            time.sleep(0.05)
        assert any(n.endswith(".json") for n in os.listdir(cps_dir)), \
            "replica never froze the idle key"
        # second delta ACKED (WAL-durable), then SIGKILL mid-stream —
        # the replica never gets to apply or drain it
        outs = _http_deltas(port, [{"key": key,
                                    "ops": [dict(o) for o in h[12:]],
                                    "timeout": 60}])
        assert outs[0].get("accepted"), outs
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        # survivors adopt: ring drops the dead node, WAL segments +
        # checkpoint pair transfer, recovery replays
        survivor = CheckerService(m, wal_dir=live_dir, capacity=128)
        ring = ring_mod.HashRing(["dead-node", "live-node"])
        plan = ring_mod.rehome_dead_replica(
            dead_dir, ring, "dead-node", {"live-node": live_dir},
            {"live-node": survivor})
        assert plan == {"live-node": [key]}
        rr = survivor.result(key, timeout=150)
        assert _pin(rr) == _pin(ref), "migrated verdict diverged"
        assert rr["seq"] == 2   # the acked-but-unapplied delta landed
        # warm handoff engaged: delta 2 — acked by the dead replica,
        # never applied by it, so the FIRST delta the adopter serves —
        # ran with ZERO fresh compiles: every program came through the
        # transferred manifest / shared disk cache (the dead replica
        # compiled it; the ledger proves the adopter never had to)
        st = programs.registry().stats()
        assert st["compiles"] == 0, st
        assert st["manifest_warms"] >= 1 or st["preloads"] >= 1, st
        assert st["hits"] >= 1, st
        assert st["load_errors"] == 0, st
        f = survivor.finalize(key, timeout=150)
        assert _pin(f) == _pin(ref)
    finally:
        if proc.poll() is None:
            proc.kill()
        if survivor is not None:
            survivor.close()
        programs.reset()
