"""checker.linear_packed — the int-config host engine (the bench's
CPU baseline). Differential against the object-config host engines on
every packed model family, plus deadline and fallback behavior."""

import pytest

from jepsen_tpu.checker import linear, linear_packed, wgl
from jepsen_tpu.histories import (
    adversarial_register_history, corrupt_history, rand_fifo_history,
    rand_gset_history, rand_queue_history, rand_register_history)
from jepsen_tpu.models import CASRegister, FIFOQueue, GSet, UnorderedQueue


CASES = [
    ("register", CASRegister(),
     lambda s: rand_register_history(n_ops=60, n_processes=5, crash_p=0.05,
                                     fail_p=0.05, seed=s)),
    ("fifo", FIFOQueue(),
     lambda s: rand_fifo_history(n_ops=40, n_processes=4, crash_p=0.05,
                                 seed=s)),
    ("uqueue", UnorderedQueue(),
     lambda s: rand_queue_history(n_ops=40, n_processes=4, crash_p=0.05,
                                  seed=s)),
    ("gset", GSet(),
     lambda s: rand_gset_history(n_ops=40, n_processes=4, crash_p=0.05,
                                 seed=s)),
]


@pytest.mark.parametrize("name,model,gen", CASES,
                         ids=[c[0] for c in CASES])
def test_packed_vs_object_engines(name, model, gen):
    for s in range(6):
        h = gen(s + 50)
        want = wgl.analysis(model, h)["valid?"]
        assert linear_packed.analysis(model, h)["valid?"] is want, (name, s)
    # register only: corrupt_history flips read values to ints
    if name == "register":
        for s in range(6):
            bad = corrupt_history(gen(s + 50), seed=s)
            want = wgl.analysis(model, bad)["valid?"]
            got = linear_packed.analysis(model, bad)
            assert got["valid?"] is want, (s, got)
            if want is False:
                assert got["op"]["f"] == "read"


def test_packed_matches_object_on_multi_key_shape():
    """The bench's north-star key shape: both host engines agree and
    the packed one is the faster (sanity, not a benchmark)."""
    h = rand_register_history(n_ops=120, n_processes=14, n_values=5,
                              crash_p=0.005, fail_p=0.05, busy=0.8,
                              seed=2024)
    assert linear.analysis(CASRegister(), h)["valid?"] is True
    assert linear_packed.analysis(CASRegister(), h)["valid?"] is True


def test_packed_deadline_reports_progress():
    from time import monotonic
    h = adversarial_register_history(n_ops=300, k_crashed=10, seed=7)
    r = linear_packed.analysis(CASRegister(), h,
                               deadline=monotonic() - 1)  # already past
    assert r["valid?"] == "unknown" and r["timeout"] is True
    assert r["events-done"] == 0


def test_packed_config_budget_reports_progress():
    """Budget exhaustion must carry the same progress keys as a
    deadline timeout — bench extrapolates the host rate from either."""
    h = adversarial_register_history(n_ops=100, k_crashed=10, seed=7)
    r = linear_packed.analysis(CASRegister(), h, max_configs=100)
    assert r["valid?"] == "unknown"
    assert "budget exceeded" in r["error"]
    assert "events-done" in r and "max-frontier" in r


def test_packed_dispatcher_attaches_final_paths():
    """Via the Checker boundary, an invalid 'packed' (or 'linear')
    verdict carries final-paths like knossos's analyses do
    (checker.clj:203-207 renders linear.svg from them)."""
    from jepsen_tpu import checker
    from jepsen_tpu.history import History, invoke_op, ok_op
    h = History.wrap([
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 2),
    ]).index()
    for algo in ("packed", "linear"):
        r = checker.linearizable(CASRegister(), algorithm=algo)\
            .check({}, h, {})
        assert r["valid?"] is False and r["analyzer"] == algo
        assert r["final-paths"], (algo, r)


def test_packed_raises_for_unpackable():
    from jepsen_tpu.models import Model
    from jepsen_tpu.parallel.encode import EncodeError

    class Weird(Model):
        def step(self, op):
            return self

    from jepsen_tpu.history import History
    with pytest.raises(EncodeError):
        linear_packed.analysis(Weird(), History.wrap([]))
