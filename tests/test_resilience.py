"""Resilient device dispatch: fault injection, watchdog supervision,
circuit breaker, and checkpointed degradation (jepsen_tpu.resilience).

Four layers, mirroring docs/resilience.md:

  * the JEPSEN_TPU_FAULTS spec grammar — strict validation (bad specs
    raise, never silently no-op) and deterministic firing;
  * the supervisor — near-zero-overhead passthrough when inactive
    (the disabled-tracer standard), watchdog wedge verdicts, retry
    budget, breaker bookkeeping;
  * the breaker lifecycle on a fake clock — closed/open/half-open,
    exponential jittered backoff, recovery probing;
  * the fault matrix — each injected fault class x the bitdense /
    sparse / sharded / pipeline dispatch paths returns verdicts
    IDENTICAL to the clean run, including a mid-search kill that
    resumes from FrontierCheckpoint, and the breaker demonstrably
    stops re-dispatch after its threshold.

Everything runs CPU-only; injected wedges block on an event the
supervisor releases, so no test waits on a real hang.
"""

import time

import pytest

from jepsen_tpu import envflags, obs, resilience
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.resilience import breaker as breaker_mod
from jepsen_tpu.resilience import faults, supervisor as sup


@pytest.fixture(autouse=True)
def _isolate():
    """Every test starts and ends with no fault plan and no breakers."""
    resilience.reset()
    yield
    resilience.reset()


def _cval(name):
    return obs.counter(name).value


# ------------------------------------------------------ fault spec


def test_fault_spec_grammar():
    rs = faults.parse_spec(
        "wedge@dispatch:2, raise@transfer:every=3, flaky@search:n=1,"
        "raise@sharded")
    assert [(r.kind, r.site, r.n, r.every) for r in rs] == [
        ("wedge", "dispatch", 2, None),
        ("raise", "transfer", None, 3),
        ("flaky", "search", 1, None),
        ("raise", "sharded", None, None),
    ]
    # firing semantics: n = first N invocations; every = every K-th
    assert rs[0].fires(1) and rs[0].fires(2) and not rs[0].fires(3)
    assert not rs[1].fires(1) and rs[1].fires(3) and rs[1].fires(6)
    assert rs[3].fires(1) and rs[3].fires(99)


@pytest.mark.parametrize("bad", [
    "nope@dispatch",            # unknown kind
    "wedge@nowhere",            # unknown site
    "wedge dispatch",           # no @
    "wedge@dispatch:zero",      # non-integer count
    "wedge@dispatch:n=0",       # non-positive
    "wedge@dispatch:x=2",       # unknown count key
    "raise@child",              # child seam only implements wedge
])
def test_fault_spec_bad_specs_raise(bad):
    """Bad specs raise, never silently no-op — and the error is an
    EnvFlagError, the namespace's one fail-loud contract."""
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec(bad)
    assert issubclass(faults.FaultSpecError, envflags.EnvFlagError)


def test_fault_plan_env_and_legacy_wedge(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@search:n=1")
    faults.reset()
    assert faults.decide("dispatch") is None
    assert faults.decide("search").kind == "raise"
    assert faults.decide("search") is None        # n=1 consumed
    # the legacy bench seam maps onto an implicit wedge@child rule
    monkeypatch.delenv("JEPSEN_TPU_FAULTS")
    monkeypatch.setenv("JEPSEN_TPU_TEST_WEDGE", "1")
    faults.reset()
    r = faults.decide("child")
    assert r is not None and r.kind == "wedge"
    assert faults.decide("dispatch") is None
    # a malformed plan raises at the read, not at some later dispatch
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "bogus")
    faults.reset()
    with pytest.raises(faults.FaultSpecError):
        faults.decide("dispatch")


# ------------------------------------------------------ supervisor


def test_supervisor_noop_overhead_pin():
    """The disabled-supervisor standard (same bar as the disabled
    tracer): a passthrough dispatch costs single-digit microseconds of
    CPU — measured ~4us; pinned with headroom for loaded CI."""
    thunk = lambda: 1  # noqa: E731
    assert sup.dispatch("dispatch", thunk) == 1
    N = 5000
    t0 = time.process_time()
    for _ in range(N):
        sup.dispatch("dispatch", thunk)
    cpu = time.process_time() - t0
    assert cpu / N < 15e-6, f"{cpu / N * 1e9:.0f}ns per no-op dispatch"


def test_supervisor_unknown_site_raises():
    with pytest.raises(ValueError, match="unknown dispatch site"):
        sup.dispatch("warp-core", lambda: 1)


def test_supervisor_malformed_spec_fails_loudly(monkeypatch):
    """A malformed JEPSEN_TPU_FAULTS value is a CONFIGURATION error:
    it propagates untouched through dispatch — never retried, never
    breaker-recorded, never degraded to host (a degrade would silently
    run zero faults while the operator believes the plan is armed)."""
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "wedge@gpu")
    faults.reset()
    with pytest.raises(faults.FaultSpecError, match="unknown site"):
        sup.dispatch("dispatch", lambda: 1, backend="fake-m")
    assert breaker_mod.breaker_for("fake-m").snapshot()["failures"] == 0
    # ... including through the full engine path: no silent host-wgl
    h = rand_register_history(n_ops=24, n_processes=3, seed=41)
    with pytest.raises(envflags.EnvFlagError):
        engine.analysis(CASRegister(), h)


def test_supervisor_flaky_retried_then_succeeds(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "flaky@search:n=1")
    faults.reset()
    calls = []
    r0 = _cval("resilience.retries")
    out = sup.dispatch("search", lambda: calls.append(1) or 7,
                       backend="fake-a")
    assert out == 7 and len(calls) == 1
    assert _cval("resilience.retries") == r0 + 1
    # the retry succeeded: the breaker saw failure-then-success, closed
    assert breaker_mod.breaker_for("fake-a").state == breaker_mod.CLOSED


def test_supervisor_flaky_budget_exhausted(monkeypatch):
    """An exhausted retry budget surfaces as DeviceUnavailable (so the
    engines' degradation handlers catch it — a persistent transient OR
    a persistent real device error must degrade, not crash the check),
    with the original failure riding `cause`."""
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "flaky@search")   # every call
    monkeypatch.setenv("JEPSEN_TPU_DISPATCH_RETRIES", "2")
    faults.reset()
    with pytest.raises(sup.DeviceUnavailable) as ei:
        sup.dispatch("search", lambda: 1)
    assert isinstance(ei.value.cause, faults.TransientFault)
    assert "after 3 attempt(s)" in ei.value.reason


def test_supervisor_real_persistent_error_degrades(monkeypatch):
    """The dying-chip mode: a REAL exception that survives the retry
    budget reaches engine.analysis as DeviceUnavailable and degrades
    to the host path — verdict preserved (docs/resilience.md)."""
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import bitdense, engine
    m = CASRegister()
    h = rand_register_history(n_ops=24, n_processes=3, seed=31)
    clean = engine.analysis(m, h)

    def chip_died(*a, **k):
        raise RuntimeError("XlaRuntimeError: chip fell off the bus")

    # watchdog env activates the supervision slow path with no faults
    monkeypatch.setenv("JEPSEN_TPU_WATCHDOG", "30")
    monkeypatch.setattr(bitdense, "_check_bitdense", chip_died)
    r = engine.analysis(m, h)
    assert r["valid?"] == clean["valid?"]
    assert r["resilience"]["degraded"] == "host-wgl"
    assert "chip fell off the bus" in r["resilience"]["reason"]


def test_supervisor_crash_not_retried(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@dispatch:n=1")
    faults.reset()
    r0 = _cval("resilience.retries")
    with pytest.raises(faults.InjectedCrash):
        sup.dispatch("dispatch", lambda: 1)
    assert _cval("resilience.retries") == r0
    # n=1 consumed: the next dispatch is clean
    assert sup.dispatch("dispatch", lambda: 5) == 5


def test_supervisor_injected_wedge_is_bounded(monkeypatch):
    """An injected wedge surfaces as DispatchWedged within the bound
    (no real hang, no leaked forever-blocked thread: the wedge worker
    blocks on an event the supervisor releases)."""
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "wedge@dispatch:n=1")
    faults.reset()
    k0 = _cval("resilience.watchdog_kills")
    t0 = time.monotonic()
    with pytest.raises(sup.DispatchWedged) as ei:
        sup.dispatch("dispatch", lambda: 1, backend="fake-w")
    assert time.monotonic() - t0 < 5.0
    assert ei.value.site == "dispatch"
    assert _cval("resilience.watchdog_kills") == k0 + 1
    # the plan's wedge event was released so the worker exited
    assert faults.active_plan().wedge_event.is_set()


def test_supervisor_watchdog_bounds_a_real_hang():
    """A thunk that outlives the watchdog becomes DispatchWedged — the
    r05 hang-forever signature as a structured verdict."""
    import threading
    release = threading.Event()
    with pytest.raises(sup.DispatchWedged):
        sup.dispatch("search", lambda: release.wait(30), watchdog=0.15)
    release.set()   # let the abandoned worker exit


# --------------------------------------------------------- breaker


def _fake_breaker(threshold=3, healthy=None):
    clk = {"t": 0.0}
    probes = {"n": 0}

    def probe():
        probes["n"] += 1
        return healthy["ok"] if healthy is not None else False

    br = breaker_mod.CircuitBreaker(
        "fake", threshold=threshold, backoff_base=1.0,
        clock=lambda: clk["t"], probe=probe)
    return br, clk, probes


def test_breaker_lifecycle_on_a_fake_clock():
    healthy = {"ok": False}
    br, clk, probes = _fake_breaker(threshold=3, healthy=healthy)
    assert br.state == breaker_mod.CLOSED and br.allow()[0]
    br.record_failure("boom 1")
    br.record_failure("boom 2")
    assert br.state == breaker_mod.CLOSED     # below threshold
    br.record_failure("boom 3")
    assert br.state == breaker_mod.OPEN
    ok, why = br.allow()
    assert not ok and "circuit breaker open" in why and probes["n"] == 0
    # backoff elapses -> half-open -> probe (unhealthy) -> re-open,
    # with the backoff DOUBLED (exponential in the re-open count)
    first_until = br.snapshot()["open_until"]
    assert 1.0 <= first_until <= 1.1 * 1.0 + 1e-9   # base x jitter<=10%
    clk["t"] = first_until + 0.01
    ok, _ = br.allow()
    assert not ok and probes["n"] == 1
    second = br.snapshot()["open_until"] - clk["t"]
    assert 2.0 <= second <= 2.2                      # doubled, jittered
    # healthy probe closes the breaker and admits the dispatch
    clk["t"] = br.snapshot()["open_until"] + 0.01
    healthy["ok"] = True
    ok, _ = br.allow()
    assert ok and probes["n"] == 2
    assert br.state == breaker_mod.CLOSED
    # success resets the failure count entirely
    br.record_failure("late")
    assert br.state == breaker_mod.CLOSED


def test_breaker_half_open_admits_one_prober():
    """While a recovery probe is in flight (HALF_OPEN), concurrent
    callers are refused — one probe per window, no stampede against
    the recovering runtime."""
    results = {}

    def slow_probe():
        # a second allow() issued MID-PROBE must refuse, not probe
        ok2, why2 = br.allow()
        results["mid"] = (ok2, why2)
        return True

    br, clk, _ = _fake_breaker(threshold=1)
    br.probe = slow_probe
    br.record_failure("boom")
    clk["t"] = 100.0                      # backoff elapsed
    ok, _ = br.allow()                    # this caller probes
    assert ok and br.state == breaker_mod.CLOSED
    mid_ok, mid_why = results["mid"]
    assert not mid_ok and "half-open" in mid_why


def test_breaker_success_resets_consecutive_count():
    br, _, _ = _fake_breaker(threshold=2)
    br.record_failure("a")
    br.record_success()
    br.record_failure("b")
    assert br.state == breaker_mod.CLOSED   # never 2 CONSECUTIVE


def test_supervisor_open_breaker_refuses_without_dispatch(monkeypatch):
    """After threshold consecutive failures the supervisor refuses
    dispatch outright: the thunk is NOT called (no re-dispatch against
    a wedged backend — the breaker's whole contract)."""
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@dispatch")
    monkeypatch.setenv("JEPSEN_TPU_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("JEPSEN_TPU_BREAKER_BACKOFF", "1000")
    faults.reset()
    for _ in range(2):
        with pytest.raises(faults.InjectedCrash):
            sup.dispatch("dispatch", lambda: 1, backend="fake-b")
    assert breaker_mod.breaker_for("fake-b").state == breaker_mod.OPEN
    ran = []
    i0 = _cval("resilience.faults_injected")
    with pytest.raises(sup.DeviceUnavailable) as ei:
        sup.dispatch("dispatch", lambda: ran.append(1), backend="fake-b")
    assert not ran                                  # never dispatched
    assert _cval("resilience.faults_injected") == i0   # nor injected
    assert "circuit breaker open" in ei.value.reason
    # the state gauge reflects the trip (0 closed / 1 half / 2 open)
    assert obs.gauge("resilience.breaker.fake-b.state").value == 2


def test_breaker_knob_validation(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_BREAKER_THRESHOLD", "0")
    with pytest.raises(envflags.EnvFlagError):
        breaker_mod.CircuitBreaker("v")
    monkeypatch.setenv("JEPSEN_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("JEPSEN_TPU_BREAKER_BACKOFF", "soon")
    with pytest.raises(envflags.EnvFlagError):
        breaker_mod.CircuitBreaker("v2")
    for bad in ("fast", "inf", "nan"):
        # non-numeric AND non-finite both raise at the read site — a
        # watchdog of inf would otherwise blow up Thread.join at every
        # dispatch, silently degrading everything to host
        monkeypatch.setenv("JEPSEN_TPU_WATCHDOG", bad)
        with pytest.raises(envflags.EnvFlagError):
            sup.dispatch("dispatch", lambda: 1, retries=0)


def test_retries_env_alone_activates_supervision(monkeypatch):
    """An operator who sets ONLY JEPSEN_TPU_DISPATCH_RETRIES gets
    retries (and breaker bookkeeping) — not a silent fast-path
    bypass."""
    monkeypatch.setenv("JEPSEN_TPU_DISPATCH_RETRIES", "2")
    calls = []

    def flaky_real():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient XlaRuntimeError")
        return 9

    assert sup.dispatch("dispatch", flaky_real, backend="fake-r") == 9
    assert len(calls) == 3
    assert breaker_mod.breaker_for("fake-r").state == breaker_mod.CLOSED


def test_breaker_backoff_resets_between_incidents():
    """Closing the breaker (recovery) ends the incident: the next trip
    starts at the BASE backoff, not the prior incident's escalation."""
    healthy = {"ok": True}
    br, clk, _ = _fake_breaker(threshold=1, healthy=healthy)
    for _ in range(4):                      # incident 1: 4 re-opens
        br.record_failure("x")
        clk["t"] = br.snapshot()["open_until"] + 0.01
        br.allow()                          # healthy probe -> CLOSED
    assert br.state == breaker_mod.CLOSED
    br.record_failure("incident 2")         # fresh trip
    width = br.snapshot()["open_until"] - clk["t"]
    assert 1.0 <= width <= 1.1 + 1e-9       # base backoff again


# ---------------------------------------------------- fault matrix
#
# Each injected fault class x dispatch path must return verdicts
# identical to the clean run. Histories are small (the engines are
# exercised, not stressed) and shared so jit cache hits keep this
# tier-1 friendly.


@pytest.fixture(scope="module")
def reg_histories():
    hs = [rand_register_history(n_ops=30, n_processes=3, crash_p=0.05,
                                fail_p=0.05, seed=s) for s in range(4)]
    hs[2] = corrupt_history(hs[2], seed=1, n_corruptions=2)
    return hs


@pytest.fixture(scope="module")
def clean_results(reg_histories):
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine
    return [engine.analysis(CASRegister(), h) for h in reg_histories]


@pytest.mark.parametrize("spec", ["raise@dispatch", "wedge@dispatch:n=2",
                                  "flaky@dispatch:n=1",
                                  "raise@transfer:every=2"])
def test_fault_matrix_bitdense_single(spec, reg_histories, clean_results,
                                      monkeypatch):
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", spec)
    resilience.reset()
    for h, clean in zip(reg_histories, clean_results):
        r = engine.analysis(CASRegister(), h)
        assert r["valid?"] == clean["valid?"], spec


def test_fault_matrix_sparse_search(monkeypatch):
    """The sparse engine path (site "search"): flaky retries on the
    device (result dict IDENTICAL, no degradation note); a persistent
    crash degrades to host with the verdict preserved."""
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode, engine
    m = CASRegister()
    hs = [rand_register_history(n_ops=30, n_processes=3, seed=s + 10)
          for s in range(2)]
    hs[1] = corrupt_history(hs[1], seed=2, n_corruptions=2)
    encs = [encode.encode(m, h) for h in hs]
    clean = [engine.check_encoded(e, capacity=64) for e in encs]

    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "flaky@search:n=1")
    resilience.reset()
    assert engine.check_encoded(encs[0], capacity=64) == clean[0]

    # a persistent crash propagates from the raw entry point ...
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@search")
    resilience.reset()
    with pytest.raises(faults.InjectedCrash):
        engine.check_encoded(encs[0], capacity=64)
    # ... and analysis(), which owns the degradation contract,
    # preserves the verdicts through the host WGL path (dispatch
    # faulted too, so the bitdense router can't dodge the matrix)
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@search,raise@dispatch")
    resilience.reset()
    for h, c in zip(hs, clean):
        r = engine.analysis(m, h)
        assert r["valid?"] == c["valid?"]
        assert r["resilience"]["degraded"] == "host-wgl"


def test_fault_matrix_sharded(monkeypatch):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import sharded
    m = CASRegister()
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("frontier",))
    h = rand_register_history(n_ops=24, n_processes=3, seed=21)
    clean = sharded.analysis(m, h, mesh, capacity=128)
    for spec in ("raise@sharded", "wedge@sharded:n=2",
                 "flaky@sharded:n=1"):
        monkeypatch.setenv("JEPSEN_TPU_FAULTS", spec)
        resilience.reset()
        r = sharded.analysis(m, h, mesh, capacity=128)
        assert r["valid?"] == clean["valid?"], spec
        if spec == "flaky@sharded:n=1":
            assert "resilience" not in r    # retried on device
        if spec == "raise@sharded":
            assert r["resilience"]["degraded"] == "host-wgl"


def test_fault_matrix_pipeline_chunk_degrades_alone(reg_histories,
                                                    monkeypatch):
    """A failed pipeline chunk degrades ONLY its keys to the host path
    (structured reason on each), the rest of the batch keeps device
    results, and verdicts match the clean serial run."""
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine
    from jepsen_tpu.parallel import pipeline as pipe
    m = CASRegister()
    clean = engine.check_batch(m, reg_histories)
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@pipeline:n=1")
    resilience.reset()
    d0 = _cval("pipeline.chunks_degraded")
    rs = pipe.check_batch_pipelined(m, reg_histories, chunk_keys=2,
                                    cache=False)
    assert [r["valid?"] for r in rs] == [c["valid?"] for c in clean]
    assert _cval("pipeline.chunks_degraded") == d0 + 1
    degraded = [r for r in rs if "resilience" in r]
    assert 1 <= len(degraded) <= 2          # one chunk's keys only
    assert all(r["resilience"]["degraded"] == "host-wgl"
               for r in degraded)


def _five_families():
    """One clean + one corrupted/contended history per packable model
    family (register, gset, unordered queue, fifo queue, mutex)."""
    from jepsen_tpu.histories import (rand_fifo_history,
                                      rand_gset_history,
                                      rand_queue_history)
    from jepsen_tpu.history import History, invoke_op, ok_op
    from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                                   UnorderedQueue)

    def _h(*ops):
        return History.wrap(ops).index()

    reg = [rand_register_history(n_ops=30, n_processes=3, seed=1),
           corrupt_history(rand_register_history(n_ops=30,
                                                 n_processes=3, seed=2),
                           seed=3, n_corruptions=2)]
    gset = [rand_gset_history(n_ops=24, n_processes=3, n_elements=5,
                              seed=s + 70) for s in range(2)]
    uq = [rand_queue_history(n_ops=24, n_processes=3, n_values=3,
                             seed=s + 80) for s in range(2)]
    fifo = [rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                              seed=s + 90) for s in range(2)]
    mutex = [_h(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
                invoke_op(0, "release", None), ok_op(0, "release", None)),
             _h(invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
                invoke_op(1, "acquire", None), ok_op(1, "acquire", None))]
    return [(CASRegister(), reg), (GSet(), gset), (UnorderedQueue(), uq),
            (FIFOQueue(), fifo), (Mutex(), mutex)]


def test_fault_matrix_five_families(monkeypatch):
    """Acceptance sweep: every packable model family returns verdicts
    identical to its clean run with a crash injected at every
    supervised dispatch site at once."""
    from jepsen_tpu.parallel import engine
    fams = _five_families()
    clean = {i: [engine.analysis(m, h) for h in hs[:3]]
             for i, (m, hs) in enumerate(fams)}
    monkeypatch.setenv(
        "JEPSEN_TPU_FAULTS",
        "raise@dispatch,raise@search,raise@transfer,raise@sharded,"
        "raise@pipeline")
    resilience.reset()
    for i, (m, hs) in enumerate(fams):
        for h, c in zip(hs[:3], clean[i]):
            r = engine.analysis(m, h)
            assert r["valid?"] == c["valid?"], type(m).__name__
            assert r["resilience"]["degraded"] == "host-wgl"


def test_mid_search_kill_resumes_from_checkpoint(monkeypatch):
    """The degradation contract's hard case: a dispatch killed
    mid-search loses no work — the FrontierCheckpoint taken before the
    failing chunk seeds the recovery (device retry first, then the
    host WGL path), and the verdict matches the clean run."""
    from jepsen_tpu.history import History
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import encode, engine
    m = CASRegister()
    ops = []
    for i in range(40):
        ops.append({"process": i % 3, "type": "invoke", "f": "write",
                    "value": i % 5})
        ops.append({"process": i % 3, "type": "ok", "f": "write",
                    "value": i % 5})
    e = encode.encode(m, History.wrap(ops))
    clean = engine.check_encoded_resumable(e, capacity=64,
                                           checkpoint_every=5)
    assert clean["valid?"] is True

    # kill every second chunk dispatch: the outer device retry
    # recovers each one from the checkpoint — no work lost
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@search:every=2")
    resilience.reset()
    cps = []
    r = engine.check_encoded_resumable(e, capacity=64,
                                       checkpoint_every=5,
                                       checkpoint_cb=cps.append,
                                       model=m)
    assert r["valid?"] is clean["valid?"] is True
    assert r["resilience"]["degraded"] == "device-resume"
    assert r["resilience"]["resumed-from-event"] > 0
    assert cps and cps[-1].event_index == e.n_returns

    # kill EVERY dispatch from chunk 2 on: the host resumes from the
    # checkpoint (device progress kept: resumed-from-event > 0)
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@search:every=1")
    resilience.reset()
    # consume chunk 1 cleanly so a checkpoint exists... every=1 kills
    # the first chunk too: start from a prior checkpoint instead
    monkeypatch.delenv("JEPSEN_TPU_FAULTS")
    resilience.reset()
    cps = []
    engine.check_encoded_resumable(e, capacity=64, checkpoint_every=5,
                                   checkpoint_cb=cps.append)
    mid = cps[2]
    assert 0 < mid.event_index < e.n_returns
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@search")
    resilience.reset()
    rk0 = _cval("resilience.recovered_keys")
    r2 = engine.check_encoded_resumable(e, capacity=64,
                                        checkpoint_every=5,
                                        resume=mid, model=m)
    assert r2["valid?"] is True
    assert r2["resilience"]["degraded"] == "host-resume"
    assert r2["resilience"]["resumed-from-event"] == mid.event_index
    assert _cval("resilience.recovered_keys") == rk0 + 1

    # without a model the failure re-raises WITH the checkpoint
    # attached, so the caller can resume later
    resilience.reset()
    with pytest.raises(sup.DISPATCH_FAILURES) as ei:
        engine.check_encoded_resumable(e, capacity=64,
                                       checkpoint_every=5, resume=mid)
    assert ei.value.checkpoint.event_index == mid.event_index


def test_pallas_mesh_fallback_survives_supervision(monkeypatch):
    """With the watchdog configured, a real pallas lowering gap on a
    multi-device mesh must STILL take the cheap XLA-closure fallback
    (bitdense._fallback_or_raise unwraps the supervisor's
    DeviceUnavailable) — not silently degrade the bucket to the
    100-300x host path just because supervision was active."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import bitdense
    from jepsen_tpu.parallel import encode as enc_mod

    hs = [rand_register_history(n_ops=24, n_processes=3, seed=s + 50)
          for s in range(4)]
    encs = [enc_mod.encode(CASRegister(), h) for h in hs]
    mesh = Mesh(np.array(jax.devices()[:4]), ("keys",))
    baseline = bitdense.check_batch_bitdense(encs, mesh=mesh,
                                             use_pallas=False)
    real = bitdense._check_bitdense_batch

    def failing_on_pallas(*args, **kw):
        if args[6]:  # use_pallas
            raise RuntimeError("Mosaic lowering gap (simulated)")
        return real(*args, **kw)

    monkeypatch.setattr(bitdense, "_check_bitdense_batch",
                        failing_on_pallas)
    monkeypatch.delenv("JEPSEN_TPU_PALLAS", raising=False)
    monkeypatch.setattr(bitdense, "_resolve_use_pallas",
                        lambda up, S, C, platform: (True, True))
    monkeypatch.setenv("JEPSEN_TPU_WATCHDOG", "30")   # supervision ON
    rs = bitdense.check_batch_bitdense(encs, mesh=mesh)
    assert [r["valid?"] for r in rs] == [r["valid?"] for r in baseline]
    for r in rs:
        assert r["closure"] == "xla-while"
        assert "pallas closure failed" in r["closure-note"]


def test_breaker_stops_redispatch_across_checks(monkeypatch):
    """After the threshold, later checks never touch the device: the
    fault counter stops moving while verdicts stay correct (host
    path), and the fallback is classed breaker-open."""
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine
    m = CASRegister()
    h = rand_register_history(n_ops=30, n_processes=3, seed=5)
    clean = engine.analysis(m, h)
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "raise@dispatch,raise@transfer")
    monkeypatch.setenv("JEPSEN_TPU_BREAKER_THRESHOLD", "2")
    monkeypatch.setenv("JEPSEN_TPU_BREAKER_BACKOFF", "1000")
    resilience.reset()
    # each check costs one dispatch failure (crashes are not retried);
    # at threshold 2 the second check trips the breaker
    for _ in range(2):
        r1 = engine.analysis(m, h)
        assert r1["valid?"] == clean["valid?"]
    import jax
    assert breaker_mod.breaker_for(jax.default_backend()).state \
        == breaker_mod.OPEN
    i0 = _cval("resilience.faults_injected")
    r2 = engine.analysis(m, h)       # breaker-refused, no dispatch
    assert r2["valid?"] == clean["valid?"]
    assert _cval("resilience.faults_injected") == i0
    assert r2["resilience"]["degraded"] == "host-wgl"
    assert "circuit breaker open" in r2["resilience"]["reason"]


def test_independent_breaker_aware_fallback(monkeypatch):
    """independent's device fallback is breaker-aware: with the
    backend's breaker open the device batch is never attempted, the
    result carries a structured breaker-open fallback, and the per-key
    path runs host-only (no per-key re-dispatch)."""
    import jax

    from jepsen_tpu import independent
    from jepsen_tpu.checker.linearizable import linearizable
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine

    h = []
    for k in ("x", "y"):
        h.append({"process": 0, "type": "invoke", "f": "write",
                  "value": independent.KV(k, 1)})
        h.append({"process": 0, "type": "ok", "f": "write",
                  "value": independent.KV(k, 1)})
    from jepsen_tpu.history import History
    h = History.wrap(h)

    monkeypatch.setenv("JEPSEN_TPU_BREAKER_BACKOFF", "1000")
    br = breaker_mod.breaker_for(jax.default_backend())
    for _ in range(br.threshold):
        br.record_failure("simulated r05 wedge")
    assert br.state == breaker_mod.OPEN

    def boom(*a, **k):
        raise AssertionError("device dispatched against an open breaker")

    monkeypatch.setattr(engine, "check_batch", boom)
    monkeypatch.setattr(engine, "analysis", boom)
    c = independent.checker(linearizable(CASRegister(), algorithm="jax"))
    fb0 = _cval("independent.device_fallbacks.breaker-open")
    r = c.check({}, h)
    assert r["valid?"] is True
    assert r["resilience"]["class"] == "breaker-open"
    assert r["resilience"]["no-redispatch"] is True
    assert "circuit breaker open" in r["device-fallback"]
    assert _cval("independent.device_fallbacks.breaker-open") == fb0 + 1
    # per-key results came from the host-forced checker
    assert all(res["analyzer"] in ("packed", "wgl")
               for res in r["results"].values())


# --------------------------------------------------- slow fault kind


def test_fault_slow_spec_grammar():
    rs = faults.parse_spec("slow@search:50, slow@dispatch:ms=10,"
                           "slow@pipeline")
    assert [(r.kind, r.site, r.ms) for r in rs] == [
        ("slow", "search", 50), ("slow", "dispatch", 10),
        ("slow", "pipeline", faults.DEFAULT_SLOW_MS)]
    # slow fires on every invocation (no n/every arg slot)
    assert rs[0].fires(1) and rs[0].fires(99)


@pytest.mark.parametrize("bad", [
    "slow@child:5",        # child seam only implements wedge
    "slow@search:banana",  # non-integer delay
    "slow@search:n=3",     # counts do not apply to slow
    "slow@search:every=2",
    "slow@search:0",       # non-positive delay
    "slow@search:ms=0",
])
def test_fault_slow_bad_specs_raise(bad):
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec(bad)


def test_fault_slow_injects_latency_preserves_result(monkeypatch):
    """slow@<site> delays the dispatch deterministically and the call
    still runs and answers — the degraded-but-alive device the
    fairness/soak scenarios need (not wedge, not crash)."""
    import time as _time
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "slow@search:ms=60")
    resilience.reset()
    before = _cval("resilience.faults_injected.search")
    t0 = _time.perf_counter()
    assert sup.dispatch("search", lambda: 42) == 42
    elapsed = _time.perf_counter() - t0
    assert elapsed >= 0.055, elapsed
    assert _cval("resilience.faults_injected.search") == before + 1
    # other sites are untouched (and fast)
    t0 = _time.perf_counter()
    assert sup.dispatch("dispatch", lambda: 7) == 7
    assert _time.perf_counter() - t0 < 0.05


def test_fault_slow_watchdog_below_delay_wedges(monkeypatch):
    """The sleep rides inside the watchdogged window: a watchdog
    bound below the injected delay fires DispatchWedged — a too-slow
    dispatch IS the r05 wedge, by definition."""
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "slow@search:ms=300")
    resilience.reset()
    with pytest.raises(sup.DispatchWedged):
        sup.dispatch("search", lambda: 42, watchdog=0.05)
    # a bound ABOVE the delay lets the slow dispatch finish
    resilience.reset()
    assert sup.dispatch("search", lambda: 42, watchdog=2.0) == 42


def test_fault_slow_verdict_identical_to_clean(monkeypatch,
                                               reg_histories,
                                               clean_results):
    """A slow device changes latency, never verdicts: the register
    sweep under slow@search matches the clean run exactly."""
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import engine as eng
    monkeypatch.setenv("JEPSEN_TPU_FAULTS", "slow@search:ms=1")
    resilience.reset()
    for i, h in enumerate(reg_histories):
        r = eng.analysis(CASRegister(), h)
        ref = clean_results[i]
        assert r["valid?"] == ref["valid?"], i
        assert r.get("op") == ref.get("op"), i
