"""Checker-suite tests — history fixtures asserted against exact result
maps, modeled on the reference's jepsen/test/jepsen/checker_test.clj."""

from jepsen_tpu import checker
from jepsen_tpu.checker.core import UNKNOWN, merge_valid
from jepsen_tpu.history import History, invoke_op, ok_op, fail_op, info_op
from jepsen_tpu.models import UnorderedQueue


def _h(*ops):
    return History.wrap(ops).index()


def test_merge_valid_lattice():
    # false > :unknown > true (checker.clj:31-45)
    assert merge_valid([True, True]) is True
    assert merge_valid([True, UNKNOWN]) == UNKNOWN
    assert merge_valid([UNKNOWN, False]) is False
    assert merge_valid([]) is True


def test_compose():
    c = checker.compose({"a": checker.noop(), "b": checker.unbridled_optimism()})
    r = c.check({}, _h())
    assert r["valid?"] is True
    assert r["a"]["valid?"] is True


def test_check_safe_catches():
    class Boom(checker.Checker):
        def check(self, test, history, opts=None):
            raise RuntimeError("boom")

    r = checker.check_safe(Boom(), {}, _h())
    assert r["valid?"] == UNKNOWN
    assert "boom" in r["error"]


def test_stats():
    h = _h(
        invoke_op(0, "read", None),
        ok_op(0, "read", 1),
        invoke_op(0, "write", 1),
        fail_op(0, "write", 1),
        invoke_op(1, "write", 2),
        info_op(1, "write", 2),
    )
    r = checker.stats().check({}, h)
    assert r["ok-count"] == 1 and r["fail-count"] == 1 and r["info-count"] == 1
    assert r["by-f"]["read"]["valid?"] is True
    assert r["by-f"]["write"]["valid?"] is False  # no ok writes
    assert r["valid?"] is False


def test_queue_checker():
    # mirrors checker_test.clj's queue test: enqueues assumed successful,
    # only ok dequeues counted
    h = _h(
        invoke_op(0, "enqueue", 1),
        ok_op(0, "enqueue", 1),
        invoke_op(1, "dequeue", None),
        ok_op(1, "dequeue", 1),
    )
    r = checker.queue(UnorderedQueue()).check({}, h)
    assert r["valid?"] is True

    bad = _h(
        invoke_op(0, "dequeue", None),
        ok_op(0, "dequeue", 9),
    )
    r = checker.queue(UnorderedQueue()).check({}, bad)
    assert r["valid?"] is False


def test_set_checker():
    h = _h(
        invoke_op(0, "add", 0),
        ok_op(0, "add", 0),
        invoke_op(1, "add", 1),
        info_op(1, "add", 1),      # unknown: recovered if read
        invoke_op(2, "add", 2),
        ok_op(2, "add", 2),
        invoke_op(3, "read", None),
        ok_op(3, "read", [0, 1]),  # 2 lost, 1 recovered
    )
    r = checker.set_checker().check({}, h)
    assert r["valid?"] is False
    assert r["lost-count"] == 1
    assert r["recovered-count"] == 1
    assert r["unexpected-count"] == 0
    assert r["attempt-count"] == 3


def test_set_checker_never_read():
    r = checker.set_checker().check({}, _h(invoke_op(0, "add", 0),
                                           ok_op(0, "add", 0)))
    assert r["valid?"] == UNKNOWN


def test_set_full():
    h = _h(
        invoke_op(0, "add", 0, time=0),
        ok_op(0, "add", 0, time=1),
        invoke_op(1, "read", None, time=2),
        ok_op(1, "read", [0], time=3),
        invoke_op(0, "add", 1, time=4),
        ok_op(0, "add", 1, time=5),
        invoke_op(1, "read", None, time=6),
        ok_op(1, "read", [0], time=7),   # 1 is absent after its add
        invoke_op(1, "read", None, time=8),
        ok_op(1, "read", [0], time=9),
    )
    r = checker.set_full().check({}, h)
    assert r["valid?"] is False
    assert r["lost"] == [1]
    assert r["stable-count"] == 1


def test_total_queue():
    h = _h(
        invoke_op(0, "enqueue", 1),
        ok_op(0, "enqueue", 1),
        invoke_op(0, "enqueue", 2),
        ok_op(0, "enqueue", 2),
        invoke_op(1, "dequeue", None),
        ok_op(1, "dequeue", 1),
        invoke_op(1, "dequeue", None),
        ok_op(1, "dequeue", 1),    # duplicated!
    )
    r = checker.total_queue().check({}, h)
    assert r["valid?"] is False      # 2 lost
    assert r["lost"] == {2: 1}
    assert r["duplicated"] == {1: 1}


def test_unique_ids():
    h = _h(
        invoke_op(0, "generate", None),
        ok_op(0, "generate", 10),
        invoke_op(0, "generate", None),
        ok_op(0, "generate", 11),
        invoke_op(0, "generate", None),
        ok_op(0, "generate", 10),
    )
    r = checker.unique_ids().check({}, h)
    assert r["valid?"] is False
    assert r["duplicated"] == {10: 2}
    assert r["range"] == [10, 11]


def test_counter():
    h = _h(
        invoke_op(0, "add", 1),
        ok_op(0, "add", 1),
        invoke_op(1, "read", None),
        ok_op(1, "read", 1),
        invoke_op(0, "add", 2),      # pending add: upper bound grows
        invoke_op(1, "read", None),
        ok_op(1, "read", 3),         # 1 <= 3 <= 3: ok
        ok_op(0, "add", 2),
        invoke_op(1, "read", None),
        ok_op(1, "read", 9),         # out of bounds
    )
    r = checker.counter().check({}, h)
    assert r["valid?"] is False
    assert len(r["errors"]) == 1
    assert r["errors"][0][1] == 9


def test_counter_failed_add_not_counted():
    h = _h(
        invoke_op(0, "add", 5),
        fail_op(0, "add", 5),
        invoke_op(1, "read", None),
        ok_op(1, "read", 0),
    )
    r = checker.counter().check({}, h)
    assert r["valid?"] is True


def test_unhandled_exceptions():
    h = _h(
        invoke_op(0, "read", None),
        info_op(0, "read", None, error="indeterminate: timeout"),
        invoke_op(0, "read", None),
        info_op(0, "read", None, error="indeterminate: timeout"),
    )
    r = checker.unhandled_exceptions().check({}, h)
    assert r["valid?"] is True
    assert r["exceptions"][0]["count"] == 2
