"""Self-healing fleet suite (ISSUE 13): WAL segment replication
(sync/async shipping, replica-copy rehome with the primary's disk
gone, torn tails on the mirror), the ownership epoch fence
(split-brain refusals, fence-before-transfer ordering, adoption
bumps), and the FleetSupervisor's detect → rehome → rejoin loop
driven deterministically with an injected fetch + clock.
"""

import os
import shutil

import pytest

from jepsen_tpu import envflags, obs
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.history import History
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import encode as enc_mod, engine
from jepsen_tpu.serve import (
    CheckerService, DeltaWAL, FleetSupervisor, SegmentReplicator,
)
from jepsen_tpu.serve import fleet as fleet_mod
from jepsen_tpu.serve import ring as ring_mod

PIN = ("valid?", "op", "fail-event", "max-frontier", "configs-stepped")


def _pin(r):
    return {k: r.get(k) for k in PIN}


def _oneshot(ops, capacity=128):
    e = enc_mod.encode(CASRegister(), History.wrap(list(ops)))
    return engine.check_encoded(e, capacity=capacity, dedupe="sort")


def _history(seed=2, corrupt=True):
    h = rand_register_history(n_ops=20, n_processes=4, n_values=3,
                              crash_p=0.05, seed=seed)
    if corrupt:
        h = corrupt_history(h, seed=1, n_corruptions=2)
    return list(h)


# ------------------------------------------------- knob validation


def test_repl_mode_validation(monkeypatch):
    assert fleet_mod.resolve_repl_mode() == "off"
    for v in ("async", "sync"):
        monkeypatch.setenv("JEPSEN_TPU_SERVE_REPL", v)
        assert fleet_mod.resolve_repl_mode() == v
    monkeypatch.setenv("JEPSEN_TPU_SERVE_REPL", "on")
    with pytest.raises(envflags.EnvFlagError, match="SERVE_REPL"):
        fleet_mod.resolve_repl_mode()
    with pytest.raises(envflags.EnvFlagError, match="replication"):
        fleet_mod.resolve_repl_mode(v="mirror")


def test_fleet_knob_validation(monkeypatch):
    monkeypatch.setenv("JEPSEN_TPU_FLEET_INTERVAL", "0")
    with pytest.raises(envflags.EnvFlagError):
        fleet_mod.resolve_fleet_interval()
    monkeypatch.setenv("JEPSEN_TPU_FLEET_THRESHOLD", "0")
    with pytest.raises(envflags.EnvFlagError):
        fleet_mod.resolve_fleet_threshold()
    monkeypatch.setenv("JEPSEN_TPU_FLEET_REHOME_RETRIES", "nope")
    with pytest.raises(envflags.EnvFlagError):
        fleet_mod.resolve_rehome_retries()


def test_service_rejects_armed_repl_without_target(tmp_path,
                                                   monkeypatch):
    """A configured replication mode with nothing wired to ship to is
    a fault-tolerance plan that protects nothing — loud, at
    construction."""
    monkeypatch.setenv("JEPSEN_TPU_SERVE_REPL", "sync")
    with pytest.raises(ValueError, match="SERVE_REPL"):
        CheckerService(CASRegister(), wal_dir=str(tmp_path / "w"))
    monkeypatch.delenv("JEPSEN_TPU_SERVE_REPL")
    repl = SegmentReplicator(DeltaWAL(str(tmp_path / "w")),
                             fleet_mod.constant_dst(
                                 str(tmp_path / "m")), mode="sync")
    with pytest.raises(ValueError, match="WAL-backed"):
        CheckerService(CASRegister(), replicator=repl)


# --------------------------------------------- ring successor math


def test_ring_successor_distinct_and_deterministic():
    r = ring_mod.HashRing(["a", "b", "c"])
    for i in range(50):
        k = ("reg", i)
        succ = r.successor(k)
        assert succ is not None and succ != r.owner(k)
        assert succ == ring_mod.HashRing(["c", "b", "a"]).successor(k)
    assert ring_mod.HashRing(["solo"]).successor("k") is None


# ------------------------------------------------- segment shipping


def _mk_service(tmp_path, name, mode=None, dst=None, **kw):
    wal_dir = str(tmp_path / name)
    repl = None
    if mode is not None:
        repl = SegmentReplicator(DeltaWAL(wal_dir),
                                 fleet_mod.constant_dst(dst),
                                 mode=mode)
    return CheckerService(CASRegister(), wal_dir=wal_dir,
                          capacity=128, replicator=repl, **kw), wal_dir


def test_sync_replication_rehome_from_replica_bit_identical(tmp_path):
    """THE acceptance pin: a replica killed mid-stream WITH ITS WAL
    DIR DELETED is rehomed from the sync-shipped segment mirror on the
    survivor, and the adopted key's verdict is bit-identical to an
    unmigrated one-shot check — including the delta acked after the
    last rotation."""
    h = _history()
    ref = _oneshot(h)
    surv_dir = str(tmp_path / "surv")
    mirror = os.path.join(surv_dir, ring_mod.REPL_SUBDIR)
    svc, dead_dir = _mk_service(tmp_path, "dead", mode="sync",
                                dst=mirror)
    key = "repl-key"
    assert svc.submit(key, h[:14], timeout=60)["accepted"]
    svc._wal.rotate(key)
    r = svc.submit(key, h[14:], timeout=60)
    assert r["accepted"] and "replicated" not in r  # sync promise met
    svc.close(drain=False)
    shutil.rmtree(dead_dir)   # the disk went with the node
    surv = CheckerService(CASRegister(), wal_dir=surv_dir,
                          capacity=128)
    try:
        ring = ring_mod.HashRing(["dead", "surv"])
        plan = ring_mod.rehome_dead_replica(
            dead_dir, ring, "dead", {"surv": surv_dir},
            {"surv": surv})
        assert plan == {"surv": [key]}
        assert obs.registry().snapshot()[
            "serve.ring.rehomes_from_replica"]["value"] >= 1
        rr = surv.result(key, timeout=120)
        assert _pin(rr) == _pin(ref) and rr["seq"] == 2
        f = surv.finalize(key, timeout=120)
        assert _pin(f) == _pin(ref)
    finally:
        surv.close()


def test_async_replication_lag_drain_and_off(tmp_path):
    h = _history(corrupt=False)
    mirror = str(tmp_path / "mirror")
    svc, _d = _mk_service(tmp_path, "src", mode="async", dst=mirror)
    try:
        assert svc.submit("ak", h, timeout=60)["accepted"]
        assert svc._repl.drain(timeout=30)
        assert obs.registry().snapshot()[
            "serve.repl_lag_keys"]["value"] == 0
        mwal = DeltaWAL(mirror)
        assert mwal.replay("ak") == svc._wal.replay("ak")
    finally:
        svc.close()
    # off mode: the hook is a no-op and ships nothing
    repl = SegmentReplicator(DeltaWAL(str(tmp_path / "o")),
                             fleet_mod.constant_dst(
                                 str(tmp_path / "om")), mode="off")
    assert repl.after_append("k") is None
    assert not os.path.exists(str(tmp_path / "om"))


def test_sync_replication_failure_degrades_ack(tmp_path,
                                               monkeypatch):
    """An unreachable successor must not block the primary ack — it
    degrades it: the answer carries ``replicated: False`` and
    serve.repl_errors moves."""
    h = _history(corrupt=False)
    mirror = str(tmp_path / "m2")
    svc, _d = _mk_service(tmp_path, "src2", mode="sync", dst=mirror)
    try:
        monkeypatch.setattr(svc._repl, "ship",
                            lambda key: (_ for _ in ()).throw(
                                OSError("mirror disk gone")))
        before = obs.registry().snapshot().get(
            "serve.repl_errors", {}).get("value", 0)
        r = svc.submit("fk", h[:10], timeout=60)
        assert r["accepted"] and r["replicated"] is False
        assert obs.registry().snapshot()[
            "serve.repl_errors"]["value"] == before + 1
    finally:
        svc.close()


def test_rehome_from_replica_with_torn_mirror_tail(tmp_path):
    """Satellite pin: the WAL's one-torn-tail-per-segment tolerance,
    re-pinned on the REPLICATION path — a mid-copy kill (or a torn
    primary tail shipped verbatim) leaves a torn final line on the
    mirror; rehome + adoption replay the acknowledged prefix and the
    verdict matches a one-shot of exactly that prefix."""
    h = _history(corrupt=False)
    surv_dir = str(tmp_path / "tsurv")
    mirror = os.path.join(surv_dir, ring_mod.REPL_SUBDIR)
    svc, dead_dir = _mk_service(tmp_path, "tdead", mode="sync",
                                dst=mirror)
    key = "torn-key"
    assert svc.submit(key, h[:10], timeout=60)["accepted"]
    assert svc.submit(key, h[10:], timeout=60)["accepted"]
    svc.close(drain=False)
    shutil.rmtree(dead_dir)
    # tear the mirror copy's final segment mid-line: the seq-2 delta
    # becomes the never-promised tail
    segs = DeltaWAL(mirror).segments(key)
    with open(segs[-1]) as fh:
        lines = fh.read().splitlines(keepends=True)
    assert len(lines) >= 3   # header + 2 deltas
    with open(segs[-1], "w") as fh:
        fh.writelines(lines[:-1])
        fh.write(lines[-1][:len(lines[-1]) // 2])
    ref = _oneshot(h[:10])
    surv = CheckerService(CASRegister(), wal_dir=surv_dir,
                          capacity=128)
    try:
        ring = ring_mod.HashRing(["tdead", "tsurv"])
        plan = ring_mod.rehome_dead_replica(
            dead_dir, ring, "tdead", {"tsurv": surv_dir},
            {"tsurv": surv})
        assert plan == {"tsurv": [key]}
        rr = surv.result(key, timeout=120)
        assert _pin(rr) == _pin(ref) and rr["seq"] == 1
        # the stream RESUMES past the torn tail: the producer's seq-2
        # retry (never acked with mirror durability... the tear) lands
        assert surv.submit(key, h[10:], seq=2,
                           timeout=60)["accepted"]
        f = surv.finalize(key, timeout=120)
        assert _pin(f) == _pin(_oneshot(h))
    finally:
        surv.close()


# ---------------------------------------------------- epoch fencing


def test_epoch_stamped_and_bumped_by_adoption(tmp_path):
    h = _history(corrupt=False)
    dirs = {n: str(tmp_path / n) for n in ("ea", "eb")}
    svcs = {n: CheckerService(CASRegister(), wal_dir=d, capacity=128)
            for n, d in dirs.items()}
    try:
        key = "ekey"
        assert svcs["ea"].submit(key, h, timeout=60)["accepted"]
        assert svcs["ea"]._wal.epoch(key) == 1
        svcs["ea"].result(key, timeout=120)
        ring_mod.transfer_key(dirs["ea"], dirs["eb"], key)
        assert svcs["eb"].adopt_keys() == [key]
        # the bump is DURABLE immediately (fresh fsynced header), not
        # at the next append
        assert svcs["eb"]._wal.epoch(key) == 2
        assert DeltaWAL(dirs["eb"]).epoch(key) == 2
        rr = svcs["eb"].result(key, timeout=120)
        assert rr["seq"] == 1
        st = svcs["eb"].status()
        krow = next(v for k, v in st["keys"].items() if "ekey" in k)
        assert krow["epoch"] == 2 and krow["state"] == "live"
    finally:
        for s in svcs.values():
            s.close()


def test_fence_refuses_stale_owner_split_brain_pin(tmp_path):
    """THE split-brain pin: a paused replica whose key was rehomed
    away resumes and keeps talking — submit, result, and finalize all
    answer the structured epoch-fence refusal, and the refusal metric
    moves. The fresh delta it tried to ack is NOT in its WAL."""
    h = _history()
    dirs = {n: str(tmp_path / n) for n in ("fa", "fb")}
    svcs = {n: CheckerService(CASRegister(), wal_dir=d, capacity=128)
            for n, d in dirs.items()}
    try:
        key = "fkey"
        assert svcs["fa"].submit(key, h[:12], timeout=60)["accepted"]
        svcs["fa"].result(key, timeout=120)
        # the rehome path fences THEN transfers ("fa" is paused, not
        # dead — exactly the case the ordering argument covers)
        ring = ring_mod.HashRing(["fa", "fb"])
        plan = ring_mod.rehome_dead_replica(
            dirs["fa"], ring, "fa", {"fb": dirs["fb"]},
            {"fb": svcs["fb"]})
        assert plan == {"fb": [key]}
        fence_doc = DeltaWAL(dirs["fa"]).fence(key)
        assert fence_doc is not None and fence_doc["epoch"] == 2
        assert fence_doc["owner"] == "fb"
        before = obs.registry().snapshot().get(
            "serve.fenced_refusals", {}).get("value", 0)
        # the resumed stale owner: all three surfaces refuse
        r = svcs["fa"].submit(key, h[12:], seq=2, timeout=10)
        assert r["fenced"] is True and r["epoch"] == 2
        assert r["owner"] == "fb" and "error" in r
        assert svcs["fa"].result(key, timeout=10)["fenced"] is True
        assert svcs["fa"].finalize(key, timeout=10)["fenced"] is True
        assert obs.registry().snapshot()[
            "serve.fenced_refusals"]["value"] >= before + 3
        # nothing below the fence was written: the refused delta is
        # not in the stale WAL
        assert [s for s, _ in DeltaWAL(dirs["fa"]).replay(key)] == [1]
        # /status shows the key fenced
        st = svcs["fa"].status()
        krow = next(v for k, v in st["keys"].items() if "fkey" in k)
        assert krow["state"] == "fenced"
        # ... while the new owner serves the stream: the producer
        # re-routes and the verdict covers everything
        assert svcs["fb"].submit(key, h[12:], seq=2,
                                 timeout=60)["accepted"]
        f = svcs["fb"].finalize(key, timeout=120)
        assert _pin(f) == _pin(_oneshot(h))
    finally:
        for s in svcs.values():
            s.close()


def test_fenced_restart_recovers_for_forensics_only(tmp_path):
    """A fenced replica that RESTARTS (the rolling-restart case)
    recovers the key from its WAL but keeps refusing producers — the
    fence outlives the process that observed it."""
    h = _history(corrupt=False)
    d = str(tmp_path / "fr")
    svc = CheckerService(CASRegister(), wal_dir=d, capacity=128)
    key = "frkey"
    assert svc.submit(key, h, timeout=60)["accepted"]
    svc.result(key, timeout=120)
    svc.close()
    DeltaWAL(d).write_fence(key, 2, owner="elsewhere")
    svc2 = CheckerService(CASRegister(), wal_dir=d, capacity=128)
    try:
        r = svc2.submit(key, h, seq=2, timeout=10)
        assert r["fenced"] is True and r["owner"] == "elsewhere"
    finally:
        svc2.close()


def test_adoption_outranks_stale_fence_on_migrate_back(tmp_path):
    """A key migrated AWAY and later BACK: the old fence (epoch 2)
    must not bind the re-adopter whose bump (epoch 3) out-ranks it —
    adoption clears it and the key serves."""
    h = _history(corrupt=False)
    dirs = {n: str(tmp_path / n) for n in ("ma", "mb")}
    svcs = {n: CheckerService(CASRegister(), wal_dir=d, capacity=128)
            for n, d in dirs.items()}
    try:
        key = "mkey"
        assert svcs["ma"].submit(key, h, timeout=60)["accepted"]
        svcs["ma"].result(key, timeout=120)
        ring_mod.transfer_key(dirs["ma"], dirs["mb"], key)
        svcs["mb"].adopt_keys()                      # epoch 2 on mb
        DeltaWAL(dirs["ma"]).write_fence(key, 2, owner="mb")
        svcs["mb"].result(key, timeout=120)
        # migrate back: transfer mb -> ma, re-adopt on a fresh ma
        svcs["ma"].close()
        ring_mod.transfer_key(dirs["mb"], dirs["ma"], key)
        svc_a2 = CheckerService(CASRegister(), wal_dir=dirs["ma"],
                                capacity=128, recover=False)
        svcs["ma"] = svc_a2
        assert svc_a2.adopt_keys() == [key]          # epoch 3: clears
        assert DeltaWAL(dirs["ma"]).fence(key) is None
        rr = svc_a2.result(key, timeout=120)
        assert _pin(rr) == _pin(_oneshot(h))
    finally:
        for s in svcs.values():
            s.close()


def test_unreadable_fence_fails_safe(tmp_path):
    from jepsen_tpu.serve.wal import _safe_name
    wal = DeltaWAL(str(tmp_path / "uf"))
    wal.append("k", 1, [])
    path = wal._fence_path(_safe_name("k"))   # no marker yet
    with open(path + ".tmp", "w") as fh:
        fh.write("{corrupt json")
    os.replace(path + ".tmp", path)
    doc = wal.fence("k")
    assert doc is not None and doc["epoch"] > 1 << 60
    assert "error" in doc


# ------------------------------------------------- fleet supervisor


class _Script:
    """Deterministic fetch: per-replica liveness flips on command."""

    def __init__(self, names):
        self.alive = {n: True for n in names}

    def __call__(self, addr, _timeout):
        return self.alive[addr]


def _mk_fleet(tmp_path, h, n=3):
    dirs = {f"n{i}": str(tmp_path / f"n{i}") for i in range(n)}
    svcs = {name: CheckerService(CASRegister(), wal_dir=d,
                                 capacity=128)
            for name, d in dirs.items()}
    return dirs, svcs


def test_supervisor_detects_rehomes_pins_and_rejoins(tmp_path):
    h = _history(corrupt=False)
    ref = _oneshot(h)
    dirs, svcs = _mk_fleet(tmp_path, h)
    script = _Script(dirs)
    clk = [0.0]
    sleeps = []
    sup = FleetSupervisor(
        {n: None for n in dirs}, dirs, services=svcs,
        interval=1.0, threshold=2, rehome_retries=2,
        fetch=script, clock=lambda: clk[0],
        sleep=sleeps.append)
    try:
        key = "supkey"
        owner = sup.owner(key)
        victim = sup.ring.owner(key)
        assert owner == victim
        assert svcs[victim].submit(key, h, timeout=60)["accepted"]
        svcs[victim].result(key, timeout=120)
        base = obs.registry().snapshot()
        # two misses -> dead -> rehome, all in deterministic ticks
        script.alive[victim] = False
        sup.tick()
        assert not sup._reps[victim].dead
        sup.tick()
        assert sup._reps[victim].dead and sup._reps[victim].rehomed
        snap = obs.registry().snapshot()
        assert snap["fleet.deaths"]["value"] \
            == base.get("fleet.deaths", {}).get("value", 0) + 1
        assert snap["fleet.rehomes"]["value"] \
            == base.get("fleet.rehomes", {}).get("value", 0) + 1
        adopter = sup.owner(key)
        assert adopter != victim and sup.pins[key] == adopter
        rr = svcs[adopter].result(key, timeout=120)
        assert _pin(rr) == _pin(ref)
        # the victim's fence landed before the transfer
        assert DeltaWAL(dirs[victim]).fence(key)["epoch"] == 2
        st = sup.status()
        assert st["replicas"][victim]["dead"] is True
        assert st["pins"] == {str(key): adopter}
        # recovery: the breaker's half-open probe re-admits it — for
        # NEW keys only; the moved key stays pinned to its adopter
        script.alive[victim] = True
        clk[0] += 3600.0
        sup.tick()
        assert not sup._reps[victim].dead
        assert obs.registry().snapshot()["fleet.rejoins"]["value"] \
            == base.get("fleet.rejoins", {}).get("value", 0) + 1
        assert sup.owner(key) == adopter   # pinned forever
        assert victim in {sup.owner(("newkey", i))
                          for i in range(200)}   # back for new keys
    finally:
        sup.stop()
        for s in svcs.values():
            s.close()


def test_supervisor_rehome_retry_backoff_and_next_tick(tmp_path):
    """A rehome whose adopter hiccups retries with bounded backoff
    inside the tick; a whole exhausted budget stays pending and the
    NEXT tick tries again (the supervisor never gives up on a dead
    replica's keys)."""
    h = _history(corrupt=False)
    dirs, svcs = _mk_fleet(tmp_path, h, n=2)
    script = _Script(dirs)
    clk = [0.0]
    sleeps = []
    sup = FleetSupervisor(
        {n: None for n in dirs}, dirs, services=svcs,
        interval=1.0, threshold=1, rehome_retries=2,
        fetch=script, clock=lambda: clk[0], sleep=sleeps.append)
    try:
        key = "rbkey"
        victim = sup.ring.owner(key)
        surv = next(n for n in dirs if n != victim)
        assert svcs[victim].submit(key, h, timeout=60)["accepted"]
        svcs[victim].result(key, timeout=120)
        calls = []
        real_adopt = svcs[surv].adopt_keys

        def flaky_adopt():
            calls.append(1)
            if len(calls) < 4:
                raise OSError("adopter disk hiccup")
            return real_adopt()

        svcs[surv].adopt_keys = flaky_adopt
        base = obs.registry().snapshot().get(
            "fleet.rehome_failures", {}).get("value", 0)
        script.alive[victim] = False
        sup.tick()   # dead + 2 failed attempts (budget exhausted)
        assert sup._reps[victim].dead
        assert not sup._reps[victim].rehomed
        assert len(calls) == 2 and sleeps  # backoff between attempts
        assert obs.registry().snapshot()[
            "fleet.rehome_failures"]["value"] == base + 2
        sup.tick()   # next tick retries: attempts 3 (fail) + 4 (ok)
        assert sup._reps[victim].rehomed
        assert sup.owner(key) == surv
    finally:
        sup.stop()
        for s in svcs.values():
            s.close()


def test_supervisor_validates_fleet_shape(tmp_path):
    with pytest.raises(ValueError, match="same fleet"):
        FleetSupervisor({"a": None}, {"b": str(tmp_path)})


def test_fleet_breakers_stay_out_of_global_trip_set(tmp_path):
    """A dead PEER must not push this process's own device
    dispatches onto the slow supervised path: the fleet's per-replica
    breakers opt out of the module _tripped fast-path set."""
    from jepsen_tpu.resilience import breaker as breaker_mod
    dirs, svcs = _mk_fleet(tmp_path, None, n=2)
    script = _Script(dirs)
    sup = FleetSupervisor({n: None for n in dirs}, dirs,
                          services=svcs, interval=1.0, threshold=1,
                          fetch=script, clock=lambda: 0.0,
                          sleep=lambda _s: None)
    try:
        victim = sorted(dirs)[0]
        script.alive[victim] = False
        sup.tick()
        assert sup._reps[victim].dead
        assert not breaker_mod.any_tripped()
    finally:
        sup.stop()
        for s in svcs.values():
            s.close()


# ------------------------------------------------ review regressions


def test_mirror_fallback_never_rehomes_live_survivors_keys(tmp_path):
    """The survivors' repl/ mirrors hold EVERY replica's shipped keys
    — the rehome fallback must move only the dead node's (a key a
    survivor holds in its OWN WAL dir is live there; 'transferring'
    it would overwrite live segments with a possibly-lagging mirror
    copy)."""
    h = _history(corrupt=False)
    dirs = {n: str(tmp_path / n) for n in ("la", "lb", "lc")}
    for d in dirs.values():
        os.makedirs(d)
    # lb holds a LIVE key, async-mirrored (lagging) into lc's repl/
    svc_b = CheckerService(CASRegister(), wal_dir=dirs["lb"],
                           capacity=128)
    assert svc_b.submit("live-key", h[:10], timeout=60)["accepted"]
    lagging = os.path.join(dirs["lc"], ring_mod.REPL_SUBDIR)
    ring_mod.transfer_key(dirs["lb"], lagging, "live-key")
    # ... and then appends MORE (the mirror now lags)
    assert svc_b.submit("live-key", h[10:], timeout=60)["accepted"]
    svc_b.result("live-key", timeout=120)
    live_replay = DeltaWAL(dirs["lb"]).replay("live-key")
    assert len(live_replay) == 2
    # the dead node's key lives only in mirrors
    dead_wal = DeltaWAL(str(tmp_path / "stage"))
    dead_wal.append("dead-key", 1, h[:10])
    dead_mirror = os.path.join(dirs["la"], ring_mod.REPL_SUBDIR)
    ring_mod.transfer_key(str(tmp_path / "stage"), dead_mirror,
                          "dead-key")
    ring = ring_mod.HashRing(["la", "lb", "lc", "dead"])
    sources = ring_mod._key_sources(str(tmp_path / "gone"), dirs)
    assert "dead-key" in sources and "live-key" not in sources
    plan = ring_mod.rehome_dead_replica(
        str(tmp_path / "gone"), ring, "dead", dirs)
    assert [k for ks in plan.values() for k in ks] == ["dead-key"]
    # the live survivor's WAL was not touched
    assert DeltaWAL(dirs["lb"]).replay("live-key") == live_replay
    svc_b.close()


def test_live_migrate_back_unfences_and_serves(tmp_path):
    """Migrate a key away and BACK between two LIVE services (no
    restart): the returning adoption must replace the fenced local
    state, out-rank + clear the stale fence, and serve — not leave
    the key refusing producers on every replica."""
    h = _history(corrupt=False)
    ref = _oneshot(h)
    dirs = {n: str(tmp_path / n) for n in ("wa", "wb")}
    svcs = {n: CheckerService(CASRegister(), wal_dir=d, capacity=128)
            for n, d in dirs.items()}
    router = ring_mod.Router(svcs, dirs)
    try:
        key = "bounce"
        src = router.owner(key)
        dst = next(n for n in dirs if n != src)
        assert router.submit(key, h, wait=True,
                             timeout=120)["valid?"] is not None
        assert router.migrate_key(key, dst)["to"] == dst
        assert svcs[src].submit(key, h, seq=2,
                                timeout=10)["fenced"] is True
        svcs[dst].result(key, timeout=120)
        # ... and back, both services LIVE the whole time
        assert router.migrate_key(key, src)["to"] == src
        assert router.owner(key) == src
        rr = svcs[src].result(key, timeout=120)
        assert _pin(rr) == _pin(ref)
        # the old owner is fenced, the returning one is not
        assert svcs[dst].submit(key, h, seq=2,
                                timeout=10)["fenced"] is True
        assert svcs[src].submit(key, h[:4], seq=2,
                                timeout=60)["accepted"]
    finally:
        for s in svcs.values():
            s.close()


def test_sync_no_destination_degrades_ack(tmp_path):
    """A sync ack must not imply successor durability when there is
    no successor to ship to (single-node ring): the answer carries
    ``replicated: False``."""
    h = _history(corrupt=False)
    repl = SegmentReplicator(
        DeltaWAL(str(tmp_path / "solo")),
        fleet_mod.ring_successor_dst(ring_mod.HashRing(["solo"]),
                                     {"solo": str(tmp_path / "solo")},
                                     "solo"),
        mode="sync")
    svc = CheckerService(CASRegister(), wal_dir=str(tmp_path / "solo"),
                         capacity=128, replicator=repl)
    try:
        r = svc.submit("nk", h[:6], timeout=60)
        assert r["accepted"] and r["replicated"] is False
        assert obs.registry().snapshot()[
            "serve.repl_no_destination"]["value"] >= 1
    finally:
        svc.close()


def test_ship_is_incremental_suffix_copy(tmp_path):
    """Later ships append only the suffix (destination size = resume
    offset): the mirror converges byte-identical and serve.repl_bytes
    grows by the delta, not the whole segment re-copied."""
    wal = DeltaWAL(str(tmp_path / "inc"))
    mirror = str(tmp_path / "inc-mirror")
    repl = SegmentReplicator(wal, fleet_mod.constant_dst(mirror),
                             mode="sync")
    h = _history(corrupt=False)
    n1 = wal.append("ik", 1, h[:10])
    assert repl.ship("ik") == 1
    base = obs.registry().snapshot()["serve.repl_bytes"]["value"]
    n2 = wal.append("ik", 2, h[10:])
    assert repl.ship("ik") == 1
    grew = obs.registry().snapshot()["serve.repl_bytes"]["value"] \
        - base
    assert grew == n2, (grew, n1, n2)   # suffix only, not n1+n2
    src = wal.segments("ik")[0]
    dst = os.path.join(mirror, os.path.basename(src))
    with open(src, "rb") as a, open(dst, "rb") as b:
        assert a.read() == b.read()
    assert repl.ship("ik") == 0   # already current
