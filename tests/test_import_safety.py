"""Engine modules must be import-safe: importing them may not touch a
JAX backend. With a wedged device runtime (the observed axon-tunnel
outage mode) backend init hangs forever, so a module-level device
array turns `import jepsen_tpu.parallel.bitdense` into a hang before
any device call — the exact failure recorded in BENCH_r03's sec_adv.

Parity note: the reference has no analogue (JVM classloading is lazy
by construction); this pins the same property for our JAX modules.
"""

import subprocess
import sys

ENGINE_MODULES = [
    "jepsen_tpu.parallel.encode",
    "jepsen_tpu.parallel.steps",
    "jepsen_tpu.parallel.dense",
    "jepsen_tpu.parallel.bitdense",
    "jepsen_tpu.parallel.engine",
    "jepsen_tpu.parallel.sharded",
    "jepsen_tpu.parallel.pallas_kernels",
    "jepsen_tpu.parallel.extend",
    # the elastic scheduling layer: the scheduler and the mesh planner
    # must import (and plan) without touching a backend — the gated
    # jax.distributed handshake only runs inside distributed_init
    "jepsen_tpu.parallel.elastic",
    "jepsen_tpu.parallel.meshplan",
    "jepsen_tpu.models",
    "jepsen_tpu.independent",
    "jepsen_tpu.serve.service",
    # the multi-tenant admission/transport/routing layers must stand
    # up (and refuse/route traffic) while the runtime is wedged
    "jepsen_tpu.serve.tenancy",
    "jepsen_tpu.serve.ingress",
    "jepsen_tpu.serve.ring",
    # the ops surface must ANSWER while the runtime is wedged — its
    # import (and the probe watch's) can never touch a backend
    "jepsen_tpu.obs.httpd",
    "jepsen_tpu.probe",
]

_PROBE = r"""
import sys
for m in {mods!r}:
    __import__(m)
import jax
backends = jax._src.xla_bridge._backends
assert not backends, f"import initialized backend(s): {{list(backends)}}"
print("IMPORT-CLEAN")
"""


def test_engine_imports_touch_no_backend():
    # Fresh interpreter, the real environment (axon plugin included):
    # if any module creates a device value at import this either trips
    # the _backends assert (healthy runtime) or hangs into the timeout
    # (wedged runtime) — both fail loudly.
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE.format(mods=ENGINE_MODULES)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stderr
    assert "IMPORT-CLEAN" in proc.stdout
