"""Tendermint suite: gowire golden vectors, validator state-machine
math, dup-validator grudges, client error mapping, and a full local
end-to-end cas-register run against the native merkleeyes server with
a linearizability check (reference: tendermint/src/jepsen/tendermint/*
+ the docker quickstart run, /root/reference/README.md:26-52)."""

from __future__ import annotations

from fractions import Fraction

import pytest

from jepsen_tpu import generator as gen
from jepsen_tpu.tendermint import client as tc
from jepsen_tpu.tendermint import core as tcore
from jepsen_tpu.tendermint import db as td
from jepsen_tpu.tendermint import gowire as w
from jepsen_tpu.tendermint import merkleeyes as me
from jepsen_tpu.tendermint import validator as tv


# ------------------------------------------------------------- gowire


def test_uvarint_golden():
    # Go binary.PutUvarint reference values
    assert w.uvarint(0) == b"\x00"
    assert w.uvarint(1) == b"\x01"
    assert w.uvarint(127) == b"\x7f"
    assert w.uvarint(128) == b"\x80\x01"
    assert w.uvarint(300) == b"\xac\x02"
    for n in (0, 1, 127, 128, 300, 2 ** 40):
        v, pos = w.read_uvarint(w.uvarint(n))
        assert v == n and pos == len(w.uvarint(n))


def test_varint_zigzag():
    # Go binary.PutVarint: zigzag(-1)=1, zigzag(1)=2
    assert w.varint(0) == b"\x00"
    assert w.varint(-1) == b"\x01"
    assert w.varint(1) == b"\x02"
    for n in (-300, -1, 0, 1, 300, -(2 ** 40)):
        v, _ = w.read_varint(w.varint(n))
        assert v == n


def test_tx_layout():
    n = bytes(range(12))
    t = w.set_tx(b"abc", b"x", nonce_=n)
    # nonce ∥ 0x01 ∥ len(3) "abc" ∥ len(1) "x"  (merkleeyes README)
    assert t == n + b"\x01\x03abc\x01x"
    t = w.cas_tx(b"k", b"1", b"2", nonce_=n)
    assert t == n + b"\x04\x01k\x011\x012"
    t = w.valset_cas_tx(5, bytes(32), 9, nonce_=n)
    assert t[12] == 0x07
    assert t[13:21] == (5).to_bytes(8, "big")


# ----------------------------------------------------------- validator


def _test_map(nodes=("n1", "n2", "n3", "n4", "n5"), **kw):
    return {"nodes": list(nodes), **kw}


def _add_transition(cfg):
    v = tv.gen_validator()
    return {"type": "add", "version": cfg["version"], "validator": v}


def test_changing_validators_rollback_on_definite_failure(monkeypatch):
    """An Unauthorized valset CAS definitely did not apply: the local
    config must roll back (no stranded prospective validator)."""
    from jepsen_tpu.tendermint import core as tcore
    cfg = tv.initial_config(_test_map())
    test = {"nodes": _test_map()["nodes"], "validator_config": [cfg]}
    t = _add_transition(cfg)

    def boom_cas(*a, **k):
        raise tcore.tc.Unauthorized(8, "version mismatch")
    monkeypatch.setattr(tcore.tc, "with_any_node",
                        lambda test_, fn, *a: boom_cas())
    nem = tcore.ChangingValidatorsNemesis()
    with pytest.raises(tcore.tc.Unauthorized):
        nem.invoke(test, {"type": "info", "f": "transition", "value": t})
    assert test["validator_config"][0] is cfg  # rolled back
    assert t["validator"]["pub_key"] not in \
        cfg["prospective_validators"]


def test_with_any_node_flags_prior_indeterminate():
    """A TxError raised after another node's network failure carries
    prior_indeterminate=True — the failed attempt may have committed,
    so the app-level rejection is not proof nothing happened."""
    calls = []

    def transport_for(test, node):
        return node

    def cas(node, *args):
        calls.append(node)
        if len(calls) == 1:
            raise OSError("timeout after send")
        raise tc.Unauthorized(8, "version mismatch")

    test = {"nodes": ["n1", "n2"], "transport_for": transport_for}
    with pytest.raises(tc.Unauthorized) as ei:
        tc.with_any_node(test, cas)
    assert ei.value.prior_indeterminate is True

    # first-attempt rejection: definitively nothing happened
    calls.clear()

    def cas2(node, *args):
        raise tc.Unauthorized(8, "version mismatch")

    with pytest.raises(tc.Unauthorized) as ei:
        tc.with_any_node(test, cas2)
    assert ei.value.prior_indeterminate is False


def test_changing_validators_keeps_prospective_on_tainted_unauthorized(
        monkeypatch):
    """Unauthorized AFTER a swallowed indeterminate attempt must not
    roll back — the change may have landed via the earlier node."""
    from jepsen_tpu.tendermint import core as tcore
    cfg = tv.initial_config(_test_map())
    test = {"nodes": _test_map()["nodes"], "validator_config": [cfg]}
    t = _add_transition(cfg)

    def tainted(*a, **k):
        e = tcore.tc.Unauthorized(8, "version mismatch")
        e.prior_indeterminate = True
        raise e
    monkeypatch.setattr(tcore.tc, "with_any_node", tainted)
    nem = tcore.ChangingValidatorsNemesis()
    with pytest.raises(tcore.tc.Unauthorized):
        nem.invoke(test, {"type": "info", "f": "transition", "value": t})
    after = test["validator_config"][0]
    assert t["validator"]["pub_key"] in after["prospective_validators"]


def test_changing_validators_keeps_prospective_on_indeterminate(monkeypatch):
    """A network error is indeterminate — the change may have landed on
    the cluster. The pre-step config (prospective validator retained)
    must survive so refresh_config can reconcile either outcome; an
    eager rollback would make a landed validator unrecognizable."""
    from jepsen_tpu.tendermint import core as tcore
    cfg = tv.initial_config(_test_map())
    test = {"nodes": _test_map()["nodes"], "validator_config": [cfg]}
    t = _add_transition(cfg)

    monkeypatch.setattr(
        tcore.tc, "with_any_node",
        lambda *a, **k: (_ for _ in ()).throw(OSError("conn reset")))
    nem = tcore.ChangingValidatorsNemesis()
    with pytest.raises(OSError):
        nem.invoke(test, {"type": "info", "f": "transition", "value": t})
    after = test["validator_config"][0]
    assert t["validator"]["pub_key"] in after["prospective_validators"]


def test_initial_config_plain():
    cfg = tv.initial_config(_test_map())
    assert len(cfg["validators"]) == 5
    assert all(v["votes"] == 2 for v in cfg["validators"].values())
    assert tv.total_votes(cfg) == 10
    tv.assert_valid(cfg)
    assert not tv.byzantine_validators(cfg)


def test_initial_config_dup_validators():
    cfg = tv.initial_config(_test_map(dup_validators=True))
    # n1 runs n2's validator; 4 validators remain
    assert len(cfg["validators"]) == 4
    assert cfg["nodes"]["n1"] == cfg["nodes"]["n2"]
    bs = tv.byzantine_validators(cfg)
    assert len(bs) == 1
    # regular dup weighting: dup gets n-2 = 2 votes of total 3n-4 = 8
    # (validator.clj:267-337 derivation with n = 4 validators)
    n = len(cfg["validators"])
    assert bs[0]["votes"] == n - 2
    assert tv.total_votes(cfg) == 3 * n - 4
    frac = tv.vote_fractions(cfg)[bs[0]["pub_key"]]
    assert frac < Fraction(1, 3)
    tv.assert_valid(cfg)


def test_initial_config_super_byzantine():
    cfg = tv.initial_config(_test_map(dup_validators=True,
                                      super_byzantine_validators=True,
                                      max_byzantine_vote_fraction=
                                      Fraction(2, 3)))
    bs = tv.byzantine_validators(cfg)
    n = len(cfg["validators"])
    assert bs[0]["votes"] == 4 * (n - 1) - 1
    frac = tv.vote_fractions(cfg)[bs[0]["pub_key"]]
    assert Fraction(1, 3) < frac < Fraction(2, 3)


def test_invariants():
    cfg = tv.initial_config(_test_map())
    # removing validators until quorum breaks must fail
    ks = sorted(cfg["validators"])
    c1 = tv.step(cfg, {"type": "remove", "pub_key": ks[0]})
    with pytest.raises(tv.IllegalTransition):
        c2 = c1
        for k in ks[1:]:
            c2 = tv.step(c2, {"type": "remove", "pub_key": k})
    # destroying a node leaves a ghost; more than 2 ghosts is illegal
    c = cfg
    gone = 0
    with pytest.raises(tv.IllegalTransition):
        for n in sorted(cfg["nodes"]):
            c = tv.step(c, {"type": "destroy", "node": n})
            gone += 1
    assert gone >= 1


def test_step_add_promotes_prospective():
    cfg = tv.initial_config(_test_map())
    v = tv.gen_validator()
    pre = tv.pre_step(cfg, {"type": "add", "validator": v})
    assert v["pub_key"] in pre["prospective_validators"]
    post = tv.post_step(pre, {"type": "add", "validator": v})
    assert v["pub_key"] in post["validators"]
    assert v["pub_key"] not in post["prospective_validators"]


def test_rand_legal_transition_always_legal():
    cfg = tv.initial_config(_test_map())
    with gen.fixed_rand(11):
        for _ in range(60):
            t = tv.rand_legal_transition(_test_map(), cfg)
            cfg = tv.step(cfg, t)  # must not raise
    tv.assert_valid(cfg)


def test_reconciliation():
    cfg = tv.initial_config(_test_map(("n1", "n2", "n3")))
    ks = sorted(cfg["validators"])
    cluster = {"version": 7,
               "validators": [{"pub_key": k, "power": 5} for k in ks[:2]]}
    merged = tv.current_config(cfg, cluster)
    assert merged["version"] == 7
    assert set(merged["validators"]) == set(ks[:2])
    assert all(v["votes"] == 5 for v in merged["validators"].values())
    # unknown cluster validator is an error
    with pytest.raises(RuntimeError, match="recognize"):
        tv.current_config(cfg, {"version": 8, "validators":
                                [{"pub_key": "FF" * 32, "power": 1}]})


def test_genesis_structure():
    cfg = tv.initial_config(_test_map(("n1", "n2")))
    g = tv.genesis(cfg)
    assert g["chain_id"] == "jepsen"
    assert len(g["validators"]) == 2
    assert all(v["power"] == "2" for v in g["validators"])


# -------------------------------------------------------- dup grudges


def test_peekaboo_grudge():
    cfg = tv.initial_config(_test_map(dup_validators=True))
    test = {"nodes": ["n1", "n2", "n3", "n4", "n5"],
            "validator_config": [cfg]}
    with gen.fixed_rand(3):
        grudge = tcore.peekaboo_dup_validators_grudge(test)(test["nodes"])
    # one of the dup pair (n1, n2) is exiled from everyone else
    exiled = [n for n in ("n1", "n2") if len(grudge.get(n, [])) == 4]
    assert len(exiled) == 1
    kept = "n1" if exiled == ["n2"] else "n2"
    assert len(grudge.get(kept, [])) == 1  # only drops the exile


def test_split_grudge():
    cfg = tv.initial_config(_test_map(dup_validators=True))
    test = {"nodes": ["n1", "n2", "n3", "n4", "n5"],
            "validator_config": [cfg]}
    with gen.fixed_rand(3):
        grudge = tcore.split_dup_validators_grudge(test)(test["nodes"])
    # two components (dup group size 2), each dropping the other side
    assert set(grudge) == set(test["nodes"])
    comp_of = {}
    for node, drops in grudge.items():
        comp_of[node] = frozenset(set(test["nodes"]) - set(drops))
    comps = set(comp_of.values())
    assert len(comps) == 2
    # dup nodes n1, n2 land in different components
    assert comp_of["n1"] != comp_of["n2"]


# ------------------------------------------------- client error mapping


@pytest.fixture(scope="module")
def server(tmp_path_factory):
    d = tmp_path_factory.mktemp("tm")
    with me.LocalServer(sock_path=str(d / "me.sock")) as srv:
        yield srv


def test_client_kv_roundtrip(server):
    t = tc.SocketTransport(("unix", server.sock_path))
    tc.write(t, "reg", 7)
    assert tc.read(t, "reg") == 7
    tc.cas(t, "reg", 7, 8)
    assert tc.read(t, "reg") == 8
    with pytest.raises(tc.Unauthorized):
        tc.cas(t, "reg", 99, 0)
    with pytest.raises(tc.BaseUnknownAddress):
        tc.cas(t, "missing", 1, 2)
    assert tc.read(t, "missing-key") is None
    assert tc.local_read(t, "reg") == 8
    # structured values round-trip (vectors, as the set workload uses)
    tc.write(t, "vec", [1, 2, 3])
    assert tc.read(t, "vec") == [1, 2, 3]


def test_client_valset_roundtrip(server):
    t = tc.SocketTransport(("unix", server.sock_path))
    vs = tc.validator_set(t)
    pk = "AB" * 32
    tc.validator_set_cas(t, vs["version"], pk, 11)
    vs2 = tc.validator_set(t)
    assert vs2["version"] == vs["version"] + 1
    assert {"pub_key": pk, "power": 11} in vs2["validators"]


def test_cas_register_client_against_server(server):
    test = {"transport_for":
            lambda t_, n_: tc.SocketTransport(("unix", server.sock_path))}
    cl = tcore.CasRegisterClient().open(test, "n1")
    from jepsen_tpu.history import Op
    ok = cl.invoke(test, Op({"type": "invoke", "f": "write",
                             "value": (1, 5), "process": 0}))
    assert ok["type"] == "ok"
    rd = cl.invoke(test, Op({"type": "invoke", "f": "read",
                             "value": (1, None), "process": 0}))
    assert rd["type"] == "ok" and tuple(rd["value"]) == (1, 5)
    bad = cl.invoke(test, Op({"type": "invoke", "f": "cas",
                              "value": (1, [9, 2]), "process": 0}))
    assert bad["type"] == "fail"
    assert bad["error"] == "precondition-failed"


def test_changing_validators_nemesis_against_server(tmp_path):
    """The changing-validators path: refresh reconciles version with
    the live cluster, valset transitions apply via CAS, failures roll
    the local config back (core.clj:225-278)."""
    from jepsen_tpu.history import Op
    with me.LocalServer(sock_path=str(tmp_path / "s.sock")) as srv:
        nodes = ["n1", "n2", "n3"]
        cfg = tv.initial_config({"nodes": nodes})
        test = {"nodes": nodes, "validator_config": [cfg],
                "ssh": {"dummy": True},
                "transport_for":
                lambda t_, n_: tc.SocketTransport(("unix", srv.sock_path))}
        # Seed the cluster with the initial validators so refresh
        # recognizes them.
        t0 = tc.SocketTransport(("unix", srv.sock_path))
        for k, v in cfg["validators"].items():
            tc.validator_set_change(t0, k, v["votes"])
        cfg2 = tcore.refresh_config(test)
        assert cfg2["version"] >= 1  # reconciled with the live valset

        nem = tcore.ChangingValidatorsNemesis().setup(test)
        with gen.fixed_rand(5):
            t = tv.rand_legal_transition(test, cfg2)
        out = nem.invoke(test, Op({"type": "info", "f": "transition",
                                   "value": t}))
        assert out["value"] == "done"

        # A valset transition with a hopelessly stale version raises and
        # rolls the local config back (no stranded prospectives).
        before = test["validator_config"][0]
        bad = {"type": "add", "version": 999_999,
               "validator": tv.gen_validator()}
        with pytest.raises(tc.Unauthorized):
            nem.invoke(test, Op({"type": "info", "f": "transition",
                                 "value": bad}))
        assert test["validator_config"][0] is before


def test_crash_nemesis_binds_sessions():
    """crash_nemesis must run daemon control inside node sessions; with
    the dummy remote every op completes rather than raising 'no session
    bound' (the regression this guards)."""
    from jepsen_tpu.history import Op
    test = {"nodes": ["n1", "n2"], "ssh": {"dummy": True}}
    nem = tcore.crash_nemesis().setup(test)
    out = nem.invoke(test, Op({"type": "info", "f": "start"}))
    assert set(out["value"]) == {"n1", "n2"}
    assert set(out["value"].values()) == {"stopped"}
    out = nem.invoke(test, Op({"type": "info", "f": "stop"}))
    assert set(out["value"].values()) == {"started"}


def test_concurrency_override():
    base = {"nodes": ["n1"], "ssh": {"dummy": True},
            "transport_for": td.local_transport_for}
    t = tcore.test_map({**base, "concurrency": 6})
    assert t["concurrency"] == 6  # multiple of 2*n honored
    # non-multiples round up to the nearest whole key-group
    t = tcore.test_map({**base, "concurrency": 3})
    assert t["concurrency"] == 4
    t = tcore.test_map({**base, "concurrency": 1})
    assert t["concurrency"] == 2


# --------------------------------------------------------- end-to-end


def test_local_cas_register_end_to_end(tmp_path):
    """The quickstart run (README.md:26-52): cas-register workload
    against the native merkleeyes, full lifecycle, linearizable."""
    from jepsen_tpu import core as jcore
    with gen.fixed_rand(42):
        t = tcore.test_map({
            "nodes": ["n1"],
            "ssh": {"dummy": True},
            "db": td.LocalMerkleeyesDB(workdir=str(tmp_path)),
            "transport_for": td.local_transport_for,
            "time_limit": 6,
            "quiesce": 0,
            "ops_per_key": 30,
            "concurrency": 4,
        })
        completed = jcore.run(t)
    res = completed["results"]
    assert res["valid?"] is True, res
    linear = res["linear"]
    assert linear["valid?"] is True
    # multiple keys were actually exercised
    history = completed["history"]
    kv_ops = [o for o in history if isinstance(o.get("value"), tuple)]
    assert len(kv_ops) > 40


def test_local_set_workload_end_to_end(tmp_path):
    from jepsen_tpu import core as jcore
    with gen.fixed_rand(7):
        t = tcore.test_map({
            "nodes": ["n1"],
            "ssh": {"dummy": True},
            "db": td.LocalMerkleeyesDB(workdir=str(tmp_path)),
            "transport_for": td.local_transport_for,
            "workload": "set",
            "time_limit": 5,
            "quiesce": 0,
            "concurrency": 4,
        })
        completed = jcore.run(t)
    res = completed["results"]
    assert res["valid?"] is True, res


def test_cli_local_run(tmp_path, monkeypatch):
    from jepsen_tpu.tendermint import cli as tcli
    monkeypatch.chdir(tmp_path)
    code = tcli.main(["test", "--local", "--node", "n1",
                      "--workload", "cas-register", "--nemesis", "none",
                      "--time-limit", "3", "--concurrency", "4"])
    assert code == 0


def test_cli_test_all_local(tmp_path, monkeypatch, capsys):
    """test-all sweeps two local configs (cas-register and set) through
    LocalMerkleeyesDB and collates both as successes (the reference's
    multi-test runner, cli.clj:478-503)."""
    from jepsen_tpu.tendermint import cli as tcli
    monkeypatch.chdir(tmp_path)
    code = tcli.main(["test-all", "--local", "--node", "n1",
                      "--workloads", "cas-register,set",
                      "--nemeses", "none",
                      "--time-limit", "3", "--concurrency", "4"])
    out = capsys.readouterr().out
    assert code == 0, out
    assert "2 successes" in out and "0 failures" in out


def test_local_kill_recover_end_to_end(tmp_path):
    """Crash-recovery e2e: the local-kill nemesis SIGKILLs the native
    merkleeyes mid-run and restarts it on the same WAL, repeatedly.
    Committed writes must survive replay — the history (with its
    connection-error fails/indeterminates) must still check
    linearizable, and the nemesis must actually have fired."""
    from jepsen_tpu import core as jcore
    with gen.fixed_rand(13):
        t = tcore.test_map({
            "nodes": ["n1"],
            "ssh": {"dummy": True},
            "db": td.LocalMerkleeyesDB(workdir=str(tmp_path)),
            "transport_for": td.local_transport_for,
            "nemesis_name": "local-kill",
            "time_limit": 8,
            "quiesce": 0,
            "ops_per_key": 30,
            "concurrency": 4,
        })
        completed = jcore.run(t)
    res = completed["results"]
    history = completed["history"]
    kills = [o for o in history
             if o.get("process") == "nemesis" and o.get("f") == "kill"
             and o.get("type") == "info" and o.get("value")]
    restarts = [o for o in history
                if o.get("process") == "nemesis"
                and o.get("f") == "restart" and o.get("value")]
    assert kills and restarts, "nemesis never fired"
    assert res["valid?"] is True, res
    assert res["linear"]["valid?"] is True


def test_local_kill_set_workload_end_to_end(tmp_path):
    """Crash-recovery e2e on the SET workload: set-full semantics must
    hold across SIGKILL/WAL-replay cycles — an element whose write was
    acknowledged before a kill must be readable after the restart."""
    from jepsen_tpu import core as jcore
    with gen.fixed_rand(29):
        t = tcore.test_map({
            "nodes": ["n1"],
            "ssh": {"dummy": True},
            "db": td.LocalMerkleeyesDB(workdir=str(tmp_path)),
            "transport_for": td.local_transport_for,
            "workload": "set",
            "nemesis_name": "local-kill",
            "time_limit": 7,
            "quiesce": 0,
            "concurrency": 4,
        })
        completed = jcore.run(t)
    res = completed["results"]
    history = completed["history"]
    assert any(o.get("process") == "nemesis" and o.get("f") == "kill"
               and o.get("value") for o in history), "nemesis never fired"
    assert res["valid?"] is True, res


def _deploy_gate():
    import os
    if not (os.path.exists("/.dockerenv")
            or os.path.exists("/run/.containerenv")
            or os.environ.get("JEPSEN_CLOCK_TESTS") == "1"):
        pytest.skip("writes /opt/jepsen on the host: container or "
                    "explicit opt-in only")


_STUB_TENDERMINT = '''\
#!/usr/bin/env python3
"""Stub tendermint: models the DEPLOY-visible behaviors of the real
binary the workload e2es cannot otherwise see — flag parsing with
persistent_peers validation, consensus-WAL replay logging on restart,
and an RPC /status endpoint that only comes up after a startup delay
(so readiness waits must actually wait). Consensus itself is out of
scope; the deployed merkleeyes daemons are the real native build."""
import json, os, re, sys, time

args = sys.argv[1:]
if "node" not in args:
    print("stub-ok")
    sys.exit(0)


def flag(name):
    return args[args.index(name) + 1] if name in args else None


home = flag("--home") or os.path.expanduser("~/.tendermint")
proxy = flag("--proxy_app") or ""
peers = flag("--p2p.persistent_peers") or ""
if not proxy.startswith(("unix://", "tcp://")):
    print("stub: bad --proxy_app %r" % proxy, flush=True)
    sys.exit(1)
plist = [p for p in peers.split(",") if p]
for p in plist:
    if not re.fullmatch(r"[0-9a-fA-F]{40}@[^@:]+:\\d+", p):
        print("stub: bad persistent peer %r" % p, flush=True)
        sys.exit(1)
print("stub: home=%s proxy_app=%s persistent_peers[%d]=%s"
      % (home, proxy, len(plist), peers), flush=True)

wal = os.path.join(home, "data", "cs.wal", "wal")
if os.path.exists(wal):
    print("stub: replayed wal bytes=%d" % os.path.getsize(wal),
          flush=True)
else:
    os.makedirs(os.path.dirname(wal), exist_ok=True)
with open(wal, "ab") as fh:
    fh.write(b"x" * 64)      # the consensus wal grows while running

port = 26657
try:
    cfg = open(os.path.join(home, "config", "config.toml")).read()
    m = re.search(r'laddr = "tcp://[^:"]*:(\\d+)"', cfg)
    if m:
        port = int(m.group(1))
except OSError:
    pass

time.sleep(float(os.environ.get("STUB_RPC_DELAY", "0.3")))
from http.server import BaseHTTPRequestHandler, HTTPServer


class H(BaseHTTPRequestHandler):
    def do_GET(self):
        body = json.dumps(
            {"result": {"node_info": {"moniker": "stub"}}}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


print("stub: rpc listening on %d" % port, flush=True)
HTTPServer(("127.0.0.1", port), H).serve_forever()
'''


def _stub_tendermint_tarball(tmp_path):
    """The stub above, packed the way the reference's tarball is
    (cli.clj:18-19)."""
    import subprocess
    dist = tmp_path / "dist"
    dist.mkdir()
    stub = dist / "tendermint"
    stub.write_text(_STUB_TENDERMINT)
    stub.chmod(0o755)
    tarball = tmp_path / "tendermint.tar.gz"
    subprocess.run(["tar", "czf", str(tarball), "-C", str(dist),
                    "tendermint"], check=True)
    return tarball


def test_tendermint_db_full_deploy_local_remote(tmp_path):
    """The FULL cluster deploy path (TendermintDB.setup/teardown,
    reference db.clj:163-219), executed for real on this machine via
    the Local remote: install_archive from a file:// tarball (stub
    tendermint binary — the real one needs a cluster image; merkleeyes
    is the real native build, uploaded and daemonized), config +
    genesis + validator-key writes, pidfile daemon management, the
    Process kill/start protocol, log_files, teardown. The remaining
    distance to the reference's docker run is just the real tendermint
    binary and five containers (docker/README.md)."""
    import json as _json
    import os

    _deploy_gate()
    tarball = _stub_tendermint_tarball(tmp_path)

    from jepsen_tpu import control as jc
    bd = str(tmp_path / "deploy")
    test = {"nodes": ["n1"], "remote": jc.LocalRemote(),
            "base_dir": bd, "concurrency": 2,
            # the stub serves RPC now: keep it off the well-known port
            # so a busy 26657 on the host can't kill the daemon
            "rpc_ports": {"n1": 26705}}
    db = td.db({"tendermint_url": f"file://{tarball}"})

    try:
        # setup inside the try: a partial failure (daemons started,
        # then nt.install crashing) must still hit the teardown
        jc.on_nodes(test, db.setup, ["n1"])
        # real native merkleeyes answering on its socket —
        # start_daemon backgrounds with no readiness wait, so poll
        from jepsen_tpu.tendermint import merkleeyes as me
        import time as _time
        deadline = _time.monotonic() + 10
        while True:
            try:
                # the with-statement's __enter__ performs the connect
                with me.client_for(("unix", td.socket_file(test)),
                                   "abci") as cl:
                    cl.echo(b"ping")
                break
            except OSError:
                if _time.monotonic() > deadline:
                    raise
                _time.sleep(0.05)
        # deploy artifacts on disk and well-formed
        genesis = _json.loads(open(bd + "/config/genesis.json").read())
        assert genesis["validators"], genesis
        vkey = _json.loads(
            open(bd + "/config/priv_validator_key.json").read())
        assert vkey, vkey
        assert "proxy_app" not in open(bd + "/config/config.toml").read()
        # both daemons hold live pids
        tm_pid = int(open(td.tendermint_pid(test)).read().strip())
        me_pid = int(open(td.merkleeyes_pid(test)).read().strip())
        os.kill(tm_pid, 0)   # raises if dead
        os.kill(me_pid, 0)
        # Process protocol: kill stops BOTH, start revives BOTH
        # (session-bound, as the crash nemesis invokes them). Death is
        # checked via /proc state, accepting zombies: when the test
        # runner is PID 1 (bare container entrypoint) the nohup'd
        # daemons reparent to it and are never reaped, so a plain
        # os.kill(pid, 0) would still succeed on the corpse.
        def _gone(pid, timeout=10.0):
            end = _time.monotonic() + timeout
            have_proc = os.path.isdir("/proc/self")
            while _time.monotonic() < end:
                if have_proc:
                    try:
                        with open(f"/proc/{pid}/stat") as fh:
                            state = fh.read().rsplit(")", 1)[1].split()[0]
                        if state == "Z":
                            return True   # unreaped corpse: dead enough
                    except (FileNotFoundError, ProcessLookupError):
                        return True
                else:
                    # no procfs (macOS): plain liveness; zombies cannot
                    # occur there for us since the runner is never PID 1
                    try:
                        os.kill(pid, 0)
                    except OSError:
                        return True
                _time.sleep(0.05)
            return False

        jc.on_nodes(test, db.kill, ["n1"])
        for dead in (tm_pid, me_pid):
            assert _gone(dead), f"pid {dead} survived db.kill"
        jc.on_nodes(test, db.start, ["n1"])
        tm_pid2 = int(open(td.tendermint_pid(test)).read().strip())
        me_pid2 = int(open(td.merkleeyes_pid(test)).read().strip())
        os.kill(tm_pid2, 0)
        os.kill(me_pid2, 0)
        assert tm_pid2 != tm_pid and me_pid2 != me_pid
        for f in db.log_files(test, "n1"):
            assert os.path.exists(f), f
    finally:
        jc.on_nodes(test, db.teardown, ["n1"])
    assert not os.path.exists(bd)


def test_tendermint_5node_deployed_cluster_e2e(tmp_path):
    """Five Local-remote nodes, each with its own base dir, driven
    through the WHOLE lifecycle by jepsen.core.run: db.cycle deploys
    TendermintDB on all five (real native merkleeyes daemons, stub
    tendermint), a cas-register workload commits through the deployed
    consensus node's socket, and the deployed-mix nemesis fires all
    three fault families — a MemNet half-partition, a validator-set
    ADD through the live app, and a crash+truncate on a non-consensus
    node — before the history checks linearizable. The closest this
    dockerless environment gets to the reference's 5-container run
    (README.md:19-35); what remains is real consensus (the real
    tendermint binary replicating between nodes)."""
    import os

    from jepsen_tpu import control as jc
    from jepsen_tpu import core as jcore
    from jepsen_tpu import net as jnet

    _deploy_gate()
    tarball = _stub_tendermint_tarball(tmp_path)

    nodes = [f"n{i}" for i in range(1, 6)]
    base_dirs = {n: str(tmp_path / "deploy" / n) for n in nodes}
    rpc_ports = {n: 26710 + i for i, n in enumerate(nodes)}
    with gen.fixed_rand(61):
        t = tcore.test_map({
            "nodes": nodes,
            "remote": jc.LocalRemote(),
            "base_dirs": base_dirs,
            "rpc_ports": rpc_ports,
            "db": td.db({"tendermint_url": f"file://{tarball}"}),
            "transport_for": td.routed_transport_for,
            "net": jnet.mem(),
            "seed_app_valset": True,   # InitChain stand-in (stub tm)
            "nemesis_name": "deployed-mix",
            "time_limit": 12,
            "quiesce": 0.5,
            "ops_per_key": 25,
        })
        # truncation must not hit the node standing in for consensus:
        # in a REAL cluster replication recovers a truncated follower,
        # but with consensus collapsed the serving node's WAL is the
        # only copy — route clients to a node the crash nemesis will
        # not truncate
        ct = next(n for _, n in t["nemesis"].routes
                  if isinstance(n, tcore.CrashTruncateNemesis))
        assert len(ct.faulty_nodes) == 1, ct.faulty_nodes
        t["consensus_node"] = next(n for n in nodes
                                   if n not in ct.faulty_nodes)
        completed = jcore.run(t)

    res = completed["results"]
    history = completed["history"]
    nem = [o for o in history if o.get("process") == "nemesis"
           and o.get("type") == "info" and o.get("value") is not None]

    def fired(f):
        return [o for o in nem if o.get("f") == f]

    assert any("Cut off" in str(o["value"]) for o in fired("start")), nem
    assert any("fully connected" in str(o["value"])
               for o in fired("stop")), nem
    assert any(o["value"] == "done" for o in fired("transition")), \
        [o for o in nem if o.get("f") == "transition"]
    crash = fired("crash")
    assert crash and all(v == "crashed"
                         for o in crash
                         for v in dict(o["value"]).values()), crash
    assert set(dict(crash[0]["value"])) == set(ct.faulty_nodes)

    # per-node deploy artifacts were snarfed from every node's own dir
    # before teardown removed them
    store = completed["store"]
    for n in nodes:
        assert os.path.exists(store.path(n, "genesis.json")), n
        assert os.path.exists(store.path(n, "merkleeyes.log")), n
        assert not os.path.exists(base_dirs[n]), "teardown left " + n

    # real work committed through the deployed socket, and the
    # partition was visible to clients (indeterminate/failed ops)
    ok_kv = [o for o in history if o.get("type") == "ok"
             and isinstance(o.get("value"), tuple)]
    assert len(ok_kv) > 40, len(ok_kv)
    assert any(str(o.get("error", "")).startswith("indeterminate:")
               or "partition" in str(o.get("error", ""))
               for o in history), "no client ever saw the partition"

    assert res["valid?"] is True, res
    assert res["linear"]["valid?"] is True


def test_stub_tendermint_fidelity_rpc_wal_peers(tmp_path):
    """The deploy-visible behaviors of the real binary, surfaced by
    the stub and asserted through the SAME product paths a real
    cluster uses: (1) RPC answers /status only after a startup delay,
    so await_tendermint_rpc (the readiness wait the reference
    approximates with a flat sleep, db.clj:204) must actually poll;
    (2) every node's --p2p.persistent_peers carries exactly the other
    nodes' 40-hex ids at gossip port 26656 and never its own
    (db.clj:75-82); (3) a restart finds the consensus WAL the previous
    run left and replays it."""
    import json as _json
    import re
    import urllib.request

    from jepsen_tpu import control as jc

    _deploy_gate()
    tarball = _stub_tendermint_tarball(tmp_path)
    nodes = ["n1", "n2", "n3"]
    test = {"nodes": nodes,
            "remote": jc.LocalRemote(),
            "base_dirs": {n: str(tmp_path / "deploy" / n) for n in nodes},
            "rpc_ports": {"n1": 26720, "n2": 26721, "n3": 26722},
            "await_rpc_timeout": 20,
            "concurrency": 2}
    db = td.db({"tendermint_url": f"file://{tarball}"})
    try:
        jc.on_nodes(test, db.setup, nodes)
        # setup returned => the readiness poll held until RPC was up
        for n in nodes:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{test['rpc_ports'][n]}/status",
                    timeout=5) as resp:
                body = _json.loads(resp.read().decode())
            assert body["result"]["node_info"]["moniker"] == "stub", body

        vc = test["validator_config"][0]
        for n in nodes:
            log = open(test["base_dirs"][n] + "/tendermint.log").read()
            m = re.search(r"persistent_peers\[(\d+)\]=(\S*)", log)
            assert m, log[-500:]
            assert int(m.group(1)) == len(nodes) - 1, m.group(0)
            entries = m.group(2).split(",")
            assert all(e.endswith(":26656") for e in entries), entries
            got_ids = {e.split("@")[0] for e in entries}
            want_ids = {vc["node_keys"][o]["id"]
                        for o in nodes if o != n}
            assert got_ids == want_ids, (n, got_ids, want_ids)

        # restart: the wal written by run #1 must be seen by run #2
        jc.on_nodes(test, db.kill, ["n1"])
        jc.on_nodes(test, db.start, ["n1"])
        jc.on_nodes(test,
                    lambda t, n: td.await_tendermint_rpc(t, n, 20),
                    ["n1"])
        log = open(test["base_dirs"]["n1"] + "/tendermint.log").read()
        m = re.search(r"replayed wal bytes=(\d+)", log)
        assert m and int(m.group(1)) >= 64, log[-500:]
    finally:
        jc.on_nodes(test, db.teardown, nodes)


REAL_TENDERMINT_URL = ("https://github.com/melekes/katas/releases/"
                       "download/0.2.0/tendermint.tar.gz")  # cli.clj:18


@pytest.mark.slow
def test_real_tendermint_binary_deploy_network_gated(tmp_path):
    """Where the network allows it, deploy the reference's ACTUAL
    tendermint tarball (cli.clj:18) on a Local-remote node: install,
    config/genesis/key writes, daemonization, RPC readiness (the
    binary's era may ignore our [rpc] table, so candidate default
    ports are polled too), liveness, teardown. Skips with the probe
    evidence on zero-egress hosts — every probe this round resolved
    neither github.com nor s3 (PROBES_r05.log)."""
    import socket
    import time as _time

    from jepsen_tpu import control as jc

    _deploy_gate()
    try:
        socket.create_connection(("github.com", 443), timeout=5).close()
    except OSError as e:
        pytest.skip(f"no network to fetch the reference tarball: {e!r}")

    nodes = ["n1"]
    test = {"nodes": nodes,
            "remote": jc.LocalRemote(),
            "base_dirs": {"n1": str(tmp_path / "deploy")},
            "rpc_ports": {"n1": 26730},
            "concurrency": 2}
    db = td.db({"tendermint_url": REAL_TENDERMINT_URL})
    try:
        jc.on_nodes(test, db.setup, nodes)
        pid = int(open(
            test["base_dirs"]["n1"] + "/tendermint.pid").read().strip())
        _time.sleep(3)
        # /proc-state liveness: a plain kill(pid, 0) passes on an
        # unreaped zombie when the runner is PID 1 (see the _gone
        # helper in the single-node deploy test)
        with open(f"/proc/{pid}/stat") as fh:
            state = fh.read().rsplit(")", 1)[1].split()[0]
        assert state != "Z", "real tendermint died at startup"
        deadline = _time.monotonic() + 60
        ready = None
        while ready is None and _time.monotonic() < deadline:
            for port in (26730, 26657, 46657):
                try:
                    socket.create_connection(("127.0.0.1", port),
                                             timeout=2).close()
                    ready = port
                    break
                except OSError:
                    _time.sleep(0.5)
        log = open(test["base_dirs"]["n1"] + "/tendermint.log").read()
        assert ready is not None, f"RPC never listened; log: {log[-800:]}"
    finally:
        jc.on_nodes(test, db.teardown, nodes)


@pytest.mark.fuzz
@pytest.mark.slow
def test_local_kill_soak(tmp_path):
    """Soak tier (deselected by default, like the reference's :perf
    tier): 45s of cas-register at concurrency 8 through continuous
    SIGKILL/WAL-replay cycles. Carries BOTH markers: the fuzz mark
    alone only deselects under the addopts default — a tier-1 style
    `-m 'not slow'` invocation overrides addopts' `-m "not fuzz"` and
    was silently pulling this ~200s container-flaky soak (noted flaky
    in CHANGES.md PR 2) into every default-suite run. Stresses reconnect storms, indeterminate
    retry tainting, and WAL recovery under load far past the smoke
    e2es; the history must still check linearizable."""
    from jepsen_tpu import core as jcore
    with gen.fixed_rand(97):
        t = tcore.test_map({
            "nodes": ["n1"],
            "ssh": {"dummy": True},
            "db": td.LocalMerkleeyesDB(workdir=str(tmp_path)),
            "transport_for": td.local_transport_for,
            "nemesis_name": "local-kill",
            "time_limit": 45,
            "quiesce": 0,
            "ops_per_key": 40,
            "concurrency": 8,
        })
        completed = jcore.run(t)
    res = completed["results"]
    history = completed["history"]
    kills = [o for o in history
             if o.get("process") == "nemesis" and o.get("f") == "kill"
             and o.get("value")]
    # a loose floor: each cycle costs 2.2s of sleeps plus kill/replay
    # wall time, and the WAL replay grows over the run — on a loaded
    # box cycles stretch; the soak's real assertion is the verdict
    assert len(kills) >= 5, f"only {len(kills)} kill cycles in 45s"
    assert res["valid?"] is True, res
