"""TPU engine tests: encoding, fixtures, differential vs host oracles,
batch/vmap, and the 8-virtual-device mesh path (conftest forces
JAX_PLATFORMS=cpu with xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

from jepsen_tpu.checker import linear, wgl
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.history import History, invoke_op, ok_op, fail_op, info_op
from jepsen_tpu.models import CASRegister, Mutex, Register, UnorderedQueue
from jepsen_tpu.parallel import encode as enc_mod
from jepsen_tpu.parallel import engine


def _h(*ops):
    return History.wrap(ops).index()


# ------------------------------------------------------------- encoding


def test_encode_basic():
    h = _h(
        invoke_op(0, "write", 1),
        invoke_op(1, "read", None),
        ok_op(0, "write", 1),
        ok_op(1, "read", 1),
    )
    e = enc_mod.encode(CASRegister(), h)
    assert e.n_returns == 2
    assert e.n_calls == 2
    assert e.n_slots == 2
    # first return: both calls open -> both slots occupied
    assert e.slot_occ[0].sum() == 2
    # second return: only the read's slot occupied
    assert e.slot_occ[1].sum() == 1
    assert e.step_name == "register"


def test_encode_crashed_call_holds_slot():
    h = _h(
        invoke_op(0, "write", 1),
        info_op(0, "write", 1),
        invoke_op(1, "write", 2),
        ok_op(1, "write", 2),
    )
    e = enc_mod.encode(Register(), h)
    assert e.n_returns == 1
    assert e.n_slots == 2   # crashed write keeps slot 0
    assert e.slot_occ[0].sum() == 2


def test_encode_unpackable_model():
    from jepsen_tpu.models import Model

    class Weird(Model):  # no pack_spec arm: host-only
        def step(self, op):
            return self

    with pytest.raises(enc_mod.EncodeError):
        enc_mod.encode(Weird(), _h())


# ------------------------------------------------------------- fixtures


FIXTURES = [
    # (model, history ops, expected valid?)
    (Register(), [
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 1)], True),
    (Register(), [
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 2)], False),
    (Register(), [
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "write", 2), invoke_op(2, "read", None),
        ok_op(2, "read", 2), ok_op(1, "write", 2)], True),
    (Register(), [
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "write", 2), info_op(1, "write", 2),
        invoke_op(2, "read", None), ok_op(2, "read", 2)], True),
    (Register(), [
        invoke_op(0, "write", 2), info_op(0, "write", 2),
        invoke_op(1, "write", 3), ok_op(1, "write", 3),
        invoke_op(2, "read", None), ok_op(2, "read", 3),
        invoke_op(2, "read", None), ok_op(2, "read", 2)], True),
    (Register(), [
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(1, "write", 2), fail_op(1, "write", 2),
        invoke_op(2, "read", None), ok_op(2, "read", 2)], False),
    (CASRegister(), [
        invoke_op(0, "write", 0), ok_op(0, "write", 0),
        invoke_op(1, "cas", [0, 1]), invoke_op(2, "cas", [1, 2]),
        ok_op(1, "cas", [0, 1]), ok_op(2, "cas", [1, 2]),
        invoke_op(0, "read", None), ok_op(0, "read", 2)], True),
    (CASRegister(), [
        invoke_op(0, "write", 0), ok_op(0, "write", 0),
        invoke_op(1, "cas", [5, 1]), ok_op(1, "cas", [5, 1])], False),
    (Mutex(), [
        invoke_op(0, "acquire", None), info_op(0, "acquire", None),
        invoke_op(1, "release", None), ok_op(1, "release", None)], True),
    (Mutex(), [
        invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
        invoke_op(1, "acquire", None), ok_op(1, "acquire", None)], False),
]


@pytest.mark.parametrize("model,ops,expect", FIXTURES)
def test_engine_fixtures(model, ops, expect):
    r = engine.analysis(model, _h(*ops))
    assert r["valid?"] is expect, r


def test_engine_counterexample_op():
    h = _h(
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 2),
    )
    r = engine.analysis(Register(), h)
    assert r["valid?"] is False
    assert r["op"]["f"] == "read" and r["op"]["value"] == 2
    # host re-search attaches a final path
    assert "final-paths" in r


def test_engine_empty():
    assert engine.analysis(Register(), _h())["valid?"] is True


def _long_invalid_history(n_ops):
    """A long valid cas-register history with an impossible read
    appended at the end — the failure is in the last few events."""
    from jepsen_tpu.histories import (rand_register_history,
                                      with_impossible_read)
    h = rand_register_history(n_ops=n_ops, n_processes=4, crash_p=0.0,
                              fail_p=0.0, n_values=4, seed=7)
    return with_impossible_read(h, value="never-written", process=97)


@pytest.mark.slow
def test_counterexample_extraction_long_history():
    """Past the 500-call whole-prefix limit the engine seeds a host
    window re-search from a device frontier checkpoint — a failing
    10k-op history still yields final-paths (the reference always
    produces them, checker.clj:203-213)."""
    from jepsen_tpu.models import CASRegister
    h = _long_invalid_history(10_000)
    r = engine.analysis(CASRegister(), h)
    assert r["valid?"] is False
    assert r["op"]["value"] == "never-written"
    assert r["final-paths"]
    # the windowed (device-seeded) path ran, not the whole-prefix one
    assert r["final-paths-window"][1] == r["fail-event"]
    for path in r["final-paths"]:
        assert path, "empty path"


@pytest.mark.slow
def test_counterexample_window_cause_precedes_window():
    """Adversarial placement for the windowed re-search: value 7 is
    written at the very start of the history, overwritten two calls
    later, and never written again; 700 valid ops follow; the final read
    returns 7. The *cause* of the failure (the overwrite) sits ~700
    return-events before the re-search window, so sound paths depend
    entirely on the device-seeded frontier carrying the correct states
    across the long prefix (engine.extract_final_paths; reference
    semantics checker.clj:203-213)."""
    from jepsen_tpu.models import CASRegister

    body = rand_register_history(n_ops=700, n_processes=4, crash_p=0.0,
                                 fail_p=0.0, n_values=5, seed=11)
    ops = [{"process": 90, "type": "invoke", "f": "write", "value": 7},
           {"process": 90, "type": "ok", "f": "write", "value": 7},
           {"process": 90, "type": "invoke", "f": "write", "value": 0},
           {"process": 90, "type": "ok", "f": "write", "value": 0}]
    ops += [dict(o) for o in body]
    ops += [{"process": 91, "type": "invoke", "f": "read", "value": None},
            {"process": 91, "type": "ok", "f": "read", "value": 7}]
    for i, o in enumerate(ops):
        o["index"], o["time"] = i, i
    h = _h(*ops)

    r = engine.analysis(CASRegister(), h)
    assert r["valid?"] is False
    assert r["op"]["f"] == "read" and r["op"]["value"] == 7
    assert r["final-paths"], r.get("final-paths-note")
    # the windowed path ran, and the window starts long after the cause
    start_ev, end_ev = r["final-paths-window"]
    # the overwrite of 7 is at return-event ~1; the window starts
    # hundreds of return events later (cas ops that legally failed are
    # dropped by encode, so returns < calls)
    assert start_ev > 400 and end_ev == r["fail-event"]

    # soundness: every path op is a genuine call from the history (no
    # fabricated linearizations), and no path linearizes a write of 7 —
    # i.e. the seeds really carried "register != 7" across the prefix
    invokes = {o["index"]: o for o in h if o["type"] == "invoke"}
    for path in r["final-paths"]:
        for step in path:
            op = step["op"]
            src = invokes[op["index"]]
            assert src["f"] == op["f"]
            assert not (op["f"] == "write" and op["value"] == 7)


def test_window_calls_drops_past_and_linearized():
    from jepsen_tpu.history import Call
    cs = [
        Call(0, 0, "write", 1, None, 0, 1, False),    # before window
        Call(1, 1, "write", 2, None, 2, 10, False),   # spans boundary
        Call(2, 2, "read", None, 2, 5, 9, False),     # in window
        Call(3, 3, "write", 3, None, 6, 20, False),   # completes past fail
    ]
    out = engine._window_calls(cs, boundary=4, fail_idx=12,
                               linearized=frozenset([1]))
    ids = [(c.process, c.crashed) for c in out]
    # call 0 dropped (past), call 1 dropped (linearized), call 3 clamped
    assert ids == [(2, False), (3, True)]
    assert out[0].index == 0 and out[1].index == 1  # renumbered


# ----------------------------------------------------------- differential


def test_differential_vs_host():
    for seed in range(20):
        h = rand_register_history(
            n_ops=60, n_processes=5, n_values=4,
            crash_p=0.06, fail_p=0.06, seed=seed + 1000,
        )
        expect = wgl.analysis(CASRegister(), h)["valid?"]
        got = engine.analysis(CASRegister(), h)
        assert got["valid?"] is expect, f"seed {seed}: {got}"

        bad = corrupt_history(h, seed=seed, n_corruptions=1)
        e1 = wgl.analysis(CASRegister(), bad)["valid?"]
        e2 = linear.analysis(CASRegister(), bad)["valid?"]
        e3 = engine.analysis(CASRegister(), bad)["valid?"]
        assert e1 == e2 == e3, f"seed {seed}: wgl={e1} linear={e2} jax={e3}"


def test_differential_gset_vs_host():
    """Device gset (bitmask state) vs host WGL on random + corrupted
    histories, covering both the bitdense (<= 7 elements) and sparse
    (> 7 elements) dispatch tiers."""
    from jepsen_tpu.histories import rand_gset_history
    from jepsen_tpu.models import GSet
    for seed in range(12):
        n_el = 5 if seed % 2 == 0 else 12  # bitdense / sparse tiers
        h = rand_gset_history(n_ops=40, n_processes=4, n_elements=n_el,
                              crash_p=0.06, seed=seed + 7000)
        expect = wgl.analysis(GSet(), h)["valid?"]
        got = engine.analysis(GSet(), h)
        assert got["valid?"] is expect, f"seed {seed}: {got}"
        assert "fallback" not in got, got

        # corrupt one ok read to include a never-added element
        ops = [dict(o) for o in h]
        for o in ops:
            if o.get("type") == "ok" and o.get("f") == "read":
                o["value"] = list(o["value"]) + [999]
                break
        bad = _h(*ops)
        e1 = wgl.analysis(GSet(), bad)["valid?"]
        e2 = engine.analysis(GSet(), bad)["valid?"]
        assert e1 == e2, f"seed {seed}: wgl={e1} jax={e2}"


def test_differential_uqueue_vs_host():
    """Device unordered-queue (packed count lanes) vs host WGL, random +
    corrupted, bitdense (4 bits) and sparse (9+ bits) tiers."""
    from jepsen_tpu.histories import rand_queue_history
    from jepsen_tpu.models import UnorderedQueue
    for seed in range(12):
        n_vals = 2 if seed % 2 == 0 else 4
        h = rand_queue_history(n_ops=40, n_processes=4, n_values=n_vals,
                               crash_p=0.06, seed=seed + 8000)
        expect = wgl.analysis(UnorderedQueue(), h)["valid?"]
        got = engine.analysis(UnorderedQueue(), h)
        assert got["valid?"] is expect, f"seed {seed}: {got}"
        assert "fallback" not in got, got

        # corrupt one ok dequeue to a never-enqueued value
        ops = [dict(o) for o in h]
        for o in ops:
            if o.get("type") == "ok" and o.get("f") == "dequeue":
                o["value"] = 777
                break
        else:
            continue
        bad = _h(*ops)
        e1 = wgl.analysis(UnorderedQueue(), bad)["valid?"]
        e2 = engine.analysis(UnorderedQueue(), bad)["valid?"]
        assert e1 == e2, f"seed {seed}: wgl={e1} jax={e2}"
        assert e1 is False  # dequeue of a never-enqueued value


def test_differential_fifo_vs_host():
    """Device strict-FIFO queue (value-code lanes, head at low bits) vs
    host WGL, random + corrupted histories."""
    from jepsen_tpu.histories import rand_fifo_history
    from jepsen_tpu.models import FIFOQueue
    for seed in range(12):
        n_vals = 2 if seed % 2 == 0 else 4
        h = rand_fifo_history(n_ops=36, n_processes=4, n_values=n_vals,
                              crash_p=0.06, seed=seed + 9100)
        expect = wgl.analysis(FIFOQueue(), h)["valid?"]
        got = engine.analysis(FIFOQueue(), h)
        assert got["valid?"] is expect, f"seed {seed}: {got}"
        assert "fallback" not in got, got

        # corrupt one ok dequeue to a never-enqueued value
        ops = [dict(o) for o in h]
        for o in ops:
            if o.get("type") == "ok" and o.get("f") == "dequeue":
                o["value"] = 777
                break
        else:
            continue
        bad = _h(*ops)
        e1 = wgl.analysis(FIFOQueue(), bad)["valid?"]
        e2 = engine.analysis(FIFOQueue(), bad)["valid?"]
        assert e1 == e2 is False, f"seed {seed}: wgl={e1} jax={e2}"


def test_fifo_order_sensitivity():
    """The FIFO device tier must reject out-of-order dequeues the
    unordered queue would accept — sequential enqueue a,b then
    dequeue b is FIFO-invalid; concurrent enqueues go either way."""
    from jepsen_tpu.models import FIFOQueue, UnorderedQueue
    seq = _h(invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
             invoke_op(0, "enqueue", "b"), ok_op(0, "enqueue", "b"),
             invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "b"))
    assert engine.analysis(UnorderedQueue(), seq)["valid?"] is True
    r = engine.analysis(FIFOQueue(), seq)
    assert r["valid?"] is False and "fallback" not in r
    assert r["op"]["f"] == "dequeue" and r["op"]["value"] == "b"

    conc = _h(invoke_op(0, "enqueue", "a"), invoke_op(1, "enqueue", "b"),
              ok_op(0, "enqueue", "a"), ok_op(1, "enqueue", "b"),
              invoke_op(2, "dequeue", None), ok_op(2, "dequeue", "b"))
    assert engine.analysis(FIFOQueue(), conc)["valid?"] is True

    # crashed dequeue pops ANY head (host value=None semantics): a
    # crashed dequeue can explain the missing "a"
    crashed = _h(invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
                 invoke_op(0, "enqueue", "b"), ok_op(0, "enqueue", "b"),
                 invoke_op(1, "dequeue", None), info_op(1, "dequeue", None),
                 invoke_op(2, "dequeue", None), ok_op(2, "dequeue", "b"))
    assert engine.analysis(FIFOQueue(), crashed)["valid?"] is True
    assert wgl.analysis(FIFOQueue(), crashed)["valid?"] is True

    # initial items (FIFOQueue.of equivalent): head is the first item
    pre = FIFOQueue(("x", "y"))
    assert engine.analysis(pre, _h(
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "x")))["valid?"] \
        is True
    assert engine.analysis(pre, _h(
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "y")))["valid?"] \
        is False


def test_crashed_dequeue_invoke_value_is_ignored():
    """A crashed dequeue's result is unknown regardless of its invoke
    value (wgl._StepOp sets value=None): the device tiers must pop
    any head / stay unconstrained, not constrain on the invoke value
    (that was a KeyError for unlaned values and a false violation for
    laned ones)."""
    from jepsen_tpu.models import FIFOQueue, UnorderedQueue
    # unlaned invoke value 5: must not KeyError
    h = _h(invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
           invoke_op(1, "dequeue", 5), info_op(1, "dequeue", 5))
    for model in (FIFOQueue(), UnorderedQueue()):
        r = engine.analysis(model, h)
        assert r["valid?"] is True and "fallback" not in r, (model, r)
    # laned invoke value: crashed deq(5) must be able to pop head 1
    h2 = _h(invoke_op(0, "enqueue", 1), ok_op(0, "enqueue", 1),
            invoke_op(0, "enqueue", 5), ok_op(0, "enqueue", 5),
            invoke_op(1, "dequeue", 5), info_op(1, "dequeue", 5),
            invoke_op(2, "dequeue", None), ok_op(2, "dequeue", 5))
    assert wgl.analysis(FIFOQueue(), h2)["valid?"] is True
    assert engine.analysis(FIFOQueue(), h2)["valid?"] is True


def test_none_is_an_ordinary_element():
    """The host models append/add literal None; the device tiers must
    agree (a None-valued ok enqueue/add encoded as a wildcard identity
    would report a false linearizability violation)."""
    from jepsen_tpu.models import FIFOQueue, GSet
    h = _h(invoke_op(0, "enqueue", None), ok_op(0, "enqueue", None),
           invoke_op(0, "dequeue", None), ok_op(0, "dequeue", None))
    assert wgl.analysis(FIFOQueue(), h)["valid?"] is True
    r = engine.analysis(FIFOQueue(), h)
    assert r["valid?"] is True and "fallback" not in r, r

    g = _h(invoke_op(0, "add", None), ok_op(0, "add", None),
           invoke_op(1, "read", None), ok_op(1, "read", [None]))
    assert wgl.analysis(GSet(), g)["valid?"] is True
    rg = engine.analysis(GSet(), g)
    assert rg["valid?"] is True and "fallback" not in rg, rg
    # and the read must CONSTRAIN: an empty read after the add completes
    g2 = _h(invoke_op(0, "add", None), ok_op(0, "add", None),
            invoke_op(1, "read", None), ok_op(1, "read", []))
    assert wgl.analysis(GSet(), g2)["valid?"] is False
    assert engine.analysis(GSet(), g2)["valid?"] is False


def test_fifo_depth_budget_falls_back_to_host():
    """> 31 bits of lane space (here 16 pending x 2-bit codes) must go
    to the host engine, loudly tagged."""
    from jepsen_tpu.models import FIFOQueue
    ops = []
    for i in range(16):
        ops.append(invoke_op(0, "enqueue", i % 3))
        ops.append(ok_op(0, "enqueue", i % 3))
    r = engine.analysis(FIFOQueue(), _h(*ops))
    assert r["valid?"] is True
    assert "fallback" in r and "fifo" in r["fallback"]


def test_crashed_wildcard_dequeues_pruned():
    """25 crashed dequeues (unknown results) pack to wildcards and are
    pruned at encode — without this each would double the mask space
    forever and overflow every capacity tier."""
    from jepsen_tpu.models import UnorderedQueue
    ops = []
    for p in range(25):
        ops.append(invoke_op(p, "dequeue", None))
        ops.append(info_op(p, "dequeue", None))
    ops += [invoke_op(30, "enqueue", "a"), ok_op(30, "enqueue", "a"),
            invoke_op(30, "dequeue", None), ok_op(30, "dequeue", "a")]
    e = enc_mod.encode(UnorderedQueue(), _h(*ops))
    assert e.n_calls == 2      # the crashed wildcards are gone
    assert e.n_slots <= 2
    r = engine.analysis(UnorderedQueue(), _h(*ops))
    assert r["valid?"] is True and "fallback" not in r


def test_gset_read_constrains_completed_adds():
    """Sequential add a; add b; read [a] is invalid (the read missed a
    completed add); an add CONCURRENT with the read goes either way."""
    from jepsen_tpu.models import GSet
    seq = _h(invoke_op(0, "add", "a"), ok_op(0, "add", "a"),
             invoke_op(0, "add", "b"), ok_op(0, "add", "b"),
             invoke_op(1, "read", None), ok_op(1, "read", ["a"]))
    assert wgl.analysis(GSet(), seq)["valid?"] is False
    assert engine.analysis(GSet(), seq)["valid?"] is False

    conc = _h(invoke_op(0, "add", "a"), ok_op(0, "add", "a"),
              invoke_op(1, "read", None),
              invoke_op(0, "add", "b"), ok_op(0, "add", "b"),
              ok_op(1, "read", ["a"]))
    assert wgl.analysis(GSet(), conc)["valid?"] is True
    assert engine.analysis(GSet(), conc)["valid?"] is True


def test_uqueue_multiset_counting():
    """Two enqueues of the same value supply exactly two dequeues."""
    from jepsen_tpu.models import UnorderedQueue
    ops = [invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
           invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
           invoke_op(1, "dequeue", None), ok_op(1, "dequeue", "a"),
           invoke_op(1, "dequeue", None), ok_op(1, "dequeue", "a")]
    assert engine.analysis(UnorderedQueue(), _h(*ops))["valid?"] is True
    ops += [invoke_op(1, "dequeue", None), ok_op(1, "dequeue", "a")]
    r = engine.analysis(UnorderedQueue(), _h(*ops))
    assert r["valid?"] is False
    assert wgl.analysis(UnorderedQueue(), _h(*ops))["valid?"] is False


def test_uqueue_counterexample_reports_observed_value():
    from jepsen_tpu.models import UnorderedQueue
    r = engine.analysis(UnorderedQueue(), _h(
        invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "a"),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "a")))
    assert r["valid?"] is False
    # the op is completion-valued: the impossible second dequeue of "a",
    # not the invocation's value=None
    assert r["op"]["f"] == "dequeue" and r["op"]["value"] == "a"


def test_gset_device_fixtures():
    from jepsen_tpu.models import GSet
    # exact-read semantics: read must observe the full set
    r = engine.analysis(GSet(), _h(
        invoke_op(0, "add", "x"), ok_op(0, "add", "x"),
        invoke_op(0, "read", None), ok_op(0, "read", ["x"])))
    assert r["valid?"] is True
    r = engine.analysis(GSet(), _h(
        invoke_op(0, "add", "x"), ok_op(0, "add", "x"),
        invoke_op(0, "read", None), ok_op(0, "read", [])))
    assert r["valid?"] is False
    # concurrent add may or may not be visible
    r = engine.analysis(GSet(), _h(
        invoke_op(0, "add", "x"), ok_op(0, "add", "x"),
        invoke_op(1, "add", "y"), invoke_op(2, "read", None),
        ok_op(2, "read", ["x", "y"]), ok_op(1, "add", "y")))
    assert r["valid?"] is True
    # > 31 distinct elements: loud host fallback, same verdict
    big = []
    for i in range(33):
        big += [invoke_op(0, "add", i), ok_op(0, "add", i)]
    r = engine.analysis(GSet(), _h(*big))
    assert r["valid?"] is True and "fallback" in r


def test_uqueue_device_fixtures():
    from jepsen_tpu.models import UnorderedQueue
    # unordered: dequeue order need not match enqueue order
    r = engine.analysis(UnorderedQueue(), _h(
        invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
        invoke_op(0, "enqueue", "b"), ok_op(0, "enqueue", "b"),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "b"),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "a")))
    assert r["valid?"] is True
    # dequeue of a value enqueued only once, twice: invalid
    r = engine.analysis(UnorderedQueue(), _h(
        invoke_op(0, "enqueue", "a"), ok_op(0, "enqueue", "a"),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "a"),
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "a")))
    assert r["valid?"] is False
    # concurrent enqueue may satisfy a concurrent dequeue
    r = engine.analysis(UnorderedQueue(), _h(
        invoke_op(0, "enqueue", "a"), invoke_op(1, "dequeue", None),
        ok_op(1, "dequeue", "a"), ok_op(0, "enqueue", "a")))
    assert r["valid?"] is True
    # crashed enqueue may supply a later dequeue
    r = engine.analysis(UnorderedQueue(), _h(
        invoke_op(0, "enqueue", "a"), info_op(0, "enqueue", "a"),
        invoke_op(1, "dequeue", None), ok_op(1, "dequeue", "a")))
    assert r["valid?"] is True
    # initial pending elements count (UnorderedQueue.of)
    r = engine.analysis(UnorderedQueue.of("x"), _h(
        invoke_op(0, "dequeue", None), ok_op(0, "dequeue", "x")))
    assert r["valid?"] is True


# ------------------------------------------------------------- batching


def test_check_batch():
    hs = [rand_register_history(n_ops=30, n_processes=3, crash_p=0.05,
                                seed=s) for s in range(8)]
    bad = corrupt_history(hs[3], seed=3, n_corruptions=2)
    expected = [wgl.analysis(CASRegister(), h)["valid?"] for h in hs[:3]] + \
               [wgl.analysis(CASRegister(), bad)["valid?"]] + \
               [wgl.analysis(CASRegister(), h)["valid?"] for h in hs[4:]]
    batch = hs[:3] + [bad] + hs[4:]
    rs = engine.check_batch(CASRegister(), batch)
    assert [r["valid?"] for r in rs] == expected


def test_check_batch_sharded_mesh():
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices())
    assert devs.size == 8, "conftest should provide 8 virtual CPU devices"
    mesh = Mesh(devs, ("keys",))
    hs = [rand_register_history(n_ops=24, n_processes=3, crash_p=0.0,
                                seed=100 + s) for s in range(8)]
    rs = engine.check_batch(CASRegister(), hs, mesh=mesh)
    assert all(r["valid?"] is True for r in rs)


def _concurrent_writes_history(m, base_process=0):
    """m concurrent writes of m distinct values (all invoked, then all
    acked): the frontier peaks at ~m * 2^(m-1) configs during the first
    closure — an adversarial single key whose cost is tunable by m."""
    ops = []
    for p in range(m):
        ops.append(invoke_op(base_process + p, "write", 1000 + p))
    for p in range(m):
        ops.append(ok_op(base_process + p, "write", 1000 + p))
    return _h(*ops)


def test_check_batch_per_key_capacity_retry():
    """One hot key among cheap ones in the sparse batch path: only the
    hot key re-runs at doubled capacity; the cheap keys' results record
    the base tier, proving they were not re-padded and re-searched at
    the hot key's capacity."""
    cheap = [rand_register_history(n_ops=20, n_processes=3, crash_p=0.0,
                                   seed=300 + s) for s in range(16)]
    hot = _concurrent_writes_history(7)       # needs ~450 configs -> 512
    doomed = _concurrent_writes_history(26)   # blows past any tier
    pre = [enc_mod.encode(CASRegister(), h)
           for h in cheap + [hot, doomed]]
    rs = engine._check_batch_sparse(CASRegister(), pre, capacity=128,
                                    max_capacity=2048)
    for r in rs[:16]:
        assert r["valid?"] is True
        assert r["capacity"] == 128, r   # never re-run at a higher tier
    assert rs[16]["valid?"] is True
    assert rs[16]["capacity"] == 512, rs[16]  # bucketed retry found 512
    assert rs[17]["valid?"] == "unknown"
    assert "overflow" in rs[17]["error"]


def test_adversarial_register_history_oracle():
    """The bench's adversarial shape (histories.adversarial_register_
    history) must be valid-by-construction under both engines, ride the
    bit-packed device path, and genuinely hold its k crashed writes
    open (slot window = k + sequential slot)."""
    from jepsen_tpu.histories import adversarial_register_history
    from jepsen_tpu.parallel import bitdense
    h = adversarial_register_history(n_ops=80, k_crashed=6, seed=3)
    e = enc_mod.encode(CASRegister(), h)
    assert e.n_slots == 7
    assert bitdense.fits_bitdense(bitdense.n_states(e), e.n_slots)
    assert wgl.analysis(CASRegister(), h)["valid?"] is True
    r = engine.analysis(CASRegister(), h)
    assert r["valid?"] is True and r.get("engine") == "bitdense"


def test_check_batch_c_tier_bucketing():
    """A wide key must not drag narrow keys into its padded mask-space:
    check_batch buckets by slot-window tier, so the narrow keys still
    ride the bit-packed engine while the 26-slot key lands in its own
    (sparse) bucket. Results per key are unchanged."""
    narrow = [rand_register_history(n_ops=30, n_processes=3, crash_p=0.02,
                                    seed=400 + s) for s in range(6)]
    bad = corrupt_history(narrow[2], seed=9, n_corruptions=2)
    doomed = _concurrent_writes_history(26)
    batch = narrow[:2] + [bad] + narrow[3:] + [doomed]
    rs = engine.check_batch(CASRegister(), batch, capacity=128,
                            max_capacity=2048)
    oracle = [wgl.analysis(CASRegister(), h)["valid?"] for h in batch[:-1]]
    assert [r["valid?"] for r in rs[:-1]] == oracle
    for r in rs[:-1]:
        assert r.get("engine") == "bitdense", r  # narrow bucket stayed fast
    assert rs[-1]["valid?"] == "unknown"         # wide bucket overflowed


def test_check_batch_exact_bucketing_matches_tier():
    """bucket="exact" (one program per distinct slot count — the
    opt-in strategy tools/perf_ab.py's bucketed line measures) must be
    verdict- and localization-identical to the default tiers on a
    mixed-C batch with an invalid key; a bogus strategy name raises."""
    batch = [rand_register_history(n_ops=40, n_processes=3 + (s % 4),
                                   crash_p=0.04, seed=500 + s)
             for s in range(8)]
    batch[5] = corrupt_history(batch[5], seed=3, n_corruptions=2)
    rs_tier = engine.check_batch(CASRegister(), batch, capacity=128,
                                 max_capacity=4096)
    rs_exact = engine.check_batch(CASRegister(), batch, capacity=128,
                                  max_capacity=4096, bucket="exact")
    strip = lambda rs: [{k: v for k, v in r.items()  # noqa: E731
                         if k != "closure"} for r in rs]
    assert strip(rs_tier) == strip(rs_exact)
    assert rs_exact[5]["valid?"] is False
    with pytest.raises(ValueError, match="bucket"):
        engine.check_batch(CASRegister(), [], bucket="bogus")
    # the env lever resolves the None default (and bad values raise
    # even on an empty batch)
    import os
    import unittest.mock as mock
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_BUCKET": "exact"}):
        rs_env = engine.check_batch(CASRegister(), batch[:2],
                                    capacity=128, max_capacity=4096)
    assert strip(rs_env) == strip(rs_tier[:2])
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_BUCKET": "bogus"}), \
            pytest.raises(ValueError, match="bucket"):
        engine.check_batch(CASRegister(), [])

    # the encoded-entry half (public for encode/device-split callers)
    # preserves input order and matches the full path
    pre = [enc_mod.encode(CASRegister(), h) for h in batch]
    rs_enc = engine.check_batch_encoded(CASRegister(), pre,
                                        capacity=128,
                                        max_capacity=4096,
                                        bucket="exact")
    assert strip(rs_enc) == strip(rs_tier)
    assert engine.check_batch_encoded(CASRegister(), []) == []


def test_dispatcher_jax_route():
    from jepsen_tpu.checker import linearizable
    h = _h(
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 1),
    )
    r = linearizable(Register(), algorithm="jax").check({}, h)
    assert r["valid?"] is True and r["analyzer"] == "jax"
    # competition now RACES jax/packed/wgl — first decisive wins
    r = linearizable(Register(), algorithm="competition").check({}, h)
    assert r["valid?"] is True
    assert r["analyzer"] in ("jax", "packed", "wgl")
    assert r["competition"]["winner"] == r["analyzer"]
    assert r["competition"]["arms"] == ["jax", "packed", "wgl"]
    # packed: the int-config host engine behind the same boundary
    r = linearizable(Register(), algorithm="packed").check({}, h)
    assert r["valid?"] is True and r["analyzer"] == "packed"
    bad = _h(invoke_op(0, "write", 1), ok_op(0, "write", 1),
             invoke_op(0, "read", None), ok_op(0, "read", 2))
    r = linearizable(Register(), algorithm="packed").check({}, bad)
    assert r["valid?"] is False and r["op"]["value"] == 2

    # packed on an unpackable model falls back to wgl, tagged
    from jepsen_tpu.models import Model

    class Weird(Model):
        def step(self, op):
            return self

    r = linearizable(Weird(), algorithm="packed").check({}, _h())
    assert r["valid?"] is True and r["analyzer"] == "wgl"


def test_batch_overflow_escalates_to_wider_tiers():
    """A key too wide for the batch program must escalate — first the
    single-key sparse engine at a higher ceiling, then the mesh-sharded
    engine — instead of returning "unknown" (the dp -> sp long-history
    escalation, SURVEY.md §5.7). State-rich FIFO keys route through the
    sparse path (S far past bitdense's cap); measured frontiers: the
    mid key peaks ~512 configs (single tier's 4x ceiling decides it),
    the giant ~1.3k (only the sharded tier's aggregate reaches it)."""
    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.histories import rand_fifo_history
    from jepsen_tpu.models import FIFOQueue

    cheap = rand_fifo_history(n_ops=40, n_processes=6, n_values=3,
                              crash_p=0.15, seed=5)    # peak ~86
    mid = rand_fifo_history(n_ops=40, n_processes=6, n_values=3,
                            crash_p=0.15, seed=1)      # peak ~512
    giant = rand_fifo_history(n_ops=40, n_processes=6, n_values=3,
                              crash_p=0.25, seed=2)    # peak ~1.3k

    rs = engine.check_batch(FIFOQueue(), [cheap, mid],
                            capacity=64, max_capacity=128)
    assert rs[0]["valid?"] is True and "escalated" not in rs[0]
    assert rs[1]["valid?"] is True, rs[1]
    assert rs[1].get("escalated") == "single", rs[1]

    mesh = Mesh(np.array(jax.devices()[:8]), ("keys",))
    rs = engine.check_batch(FIFOQueue(), [cheap, giant],
                            capacity=64, max_capacity=128, mesh=mesh)
    assert rs[0]["valid?"] is True
    assert rs[1]["valid?"] is True, rs[1]
    assert rs[1].get("escalated") == "sharded", rs[1]

    # without a mesh the giant is honestly unknown, with the error tagged
    rs = engine.check_batch(FIFOQueue(), [giant],
                            capacity=64, max_capacity=128)
    assert rs[0]["valid?"] == "unknown"
    assert "error" in rs[0]


def test_escalation_crash_is_loud(monkeypatch, caplog):
    """A broken sharded escalation tier must warn loudly and tag the
    result — never silently degrade a key to "unknown" (the same rule
    independent.py enforces for its device fallback)."""
    import logging

    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.histories import rand_fifo_history
    from jepsen_tpu.models import FIFOQueue
    from jepsen_tpu.parallel import sharded

    def boom(*a, **k):
        raise RuntimeError("sharded tier exploded")

    monkeypatch.setattr(sharded, "check_encoded_sharded", boom)
    giant = rand_fifo_history(n_ops=40, n_processes=6, n_values=3,
                              crash_p=0.25, seed=2)
    mesh = Mesh(np.array(jax.devices()[:8]), ("keys",))
    with caplog.at_level(logging.WARNING,
                         logger="jepsen_tpu.parallel.engine"):
        rs = engine.check_batch(FIFOQueue(), [giant],
                                capacity=64, max_capacity=128, mesh=mesh)
    assert rs[0]["valid?"] == "unknown"
    assert "sharded tier exploded" in rs[0].get("escalation-error", "")
    assert "escalation tiers exhausted" in rs[0]["error"]
    assert any("sharded escalation tier crashed" in r.message
               for r in caplog.records)

def test_escalation_single_tier_pinned_to_callers_mesh(monkeypatch):
    """The single-key escalation tier must run on the caller's mesh,
    never on the default backend — the batch and sharded paths keep
    that invariant (the default backend can be a wedged TPU runtime
    while we deliberately run on a CPU mesh), and a batch-overflow key
    previously broke it right in the middle of the hardened path."""
    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.histories import rand_fifo_history
    from jepsen_tpu.models import FIFOQueue

    seen = {}
    real = engine.check_encoded

    def spy(e, capacity=1024, max_capacity=1 << 20, device=None, **kw):
        seen["device"] = device
        return real(e, capacity=capacity, max_capacity=max_capacity,
                    device=device, **kw)

    monkeypatch.setattr(engine, "check_encoded", spy)
    mid = rand_fifo_history(n_ops=40, n_processes=6, n_values=3,
                            crash_p=0.15, seed=1)     # peak ~512
    mesh = Mesh(np.array(jax.devices()[4:8]), ("keys",))
    rs = engine.check_batch(FIFOQueue(), [mid],
                            capacity=64, max_capacity=128, mesh=mesh)
    assert rs[0]["valid?"] is True
    assert rs[0].get("escalated") == "single", rs[0]
    assert seen["device"] == np.asarray(mesh.devices).flat[0]


def test_check_encoded_explicit_device_placement():
    """check_encoded(device=...) places every input on that device and
    reaches the same verdict as the default-backend path."""
    import jax

    from jepsen_tpu.histories import rand_fifo_history
    from jepsen_tpu.models import FIFOQueue

    h = rand_fifo_history(n_ops=30, n_processes=4, n_values=3,
                          crash_p=0.05, seed=3)
    e = enc_mod.encode(FIFOQueue(), h)
    dev = jax.devices()[5]
    xs = engine._xs_from_encoded(e, dev)
    for name, a in xs.items():
        assert a.devices() == {dev}, (name, a.devices())
    r_pinned = engine.check_encoded(e, device=dev)
    r_default = engine.check_encoded(e)
    assert r_pinned["valid?"] == r_default["valid?"]
    # the resumable arm keeps the same invariant: chunks and carries
    # placed on the given device, same verdict
    r_res = engine.check_encoded_resumable(e, checkpoint_every=8,
                                           device=dev)
    assert r_res["valid?"] == r_default["valid?"]
    cp = engine.FrontierCheckpoint(
        0, 64, e.step_name, engine.history_digest(e),
        np.zeros(64, np.int32), np.zeros(64, np.uint32),
        np.zeros(64, np.uint32), np.arange(64) < 1, True, -1, 1, 0)
    for a in cp.carry(dev):
        assert a.devices() == {dev}, a.devices()

def test_device_false_invalid_escalates_to_host_recheck(monkeypatch):
    """A fabricated device-invalid on a genuinely valid key must END in
    the correct verdict: the host prefix re-search contradicts the
    device, the bounded full-host recheck decides valid, and the device
    verdict is overridden (tagged engine-disagreement) instead of
    shipping "invalid, no paths"."""
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister
    from jepsen_tpu.parallel import bitdense

    model = CASRegister()
    h = rand_register_history(n_ops=60, n_processes=4, crash_p=0.01,
                              fail_p=0.05, seed=21)
    e = enc_mod.encode(model, h)
    fake = {"valid?": False, "engine": "bitdense",
            "fail-event": e.n_returns - 1}
    fake.update(enc_mod.fail_op_fields(e, e.n_returns - 1))
    monkeypatch.setattr(bitdense, "check_encoded_bitdense",
                        lambda *a, **k: dict(fake))
    r = engine.analysis(model, h)
    assert r["valid?"] is True, r
    assert "engine-disagreement" in r, r
    assert "overridden" in r["engine-disagreement"]
    # the device's stale counterexample fields must not survive on a
    # valid verdict
    assert "op" not in r and "fail-event" not in r, r


def test_device_false_invalid_long_history_window_branch():
    """Same escalation through the >500-call window/seed machinery: a
    fabricated fail event on a valid key means SOME frontier seed
    linearizes its window through the "failure" — that contradiction
    must escalate to the recheck, not ship near-miss paths from the
    dead-end seeds. max_seeds covers the whole frontier here so the
    surviving lineage is guaranteed to be sampled (at the default 8 the
    outcome would depend on frontier row order)."""
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister

    model = CASRegister()
    h = rand_register_history(n_ops=700, n_processes=4, crash_p=0.005,
                              fail_p=0.03, seed=9)
    e = enc_mod.encode(model, h)
    assert e.n_calls > 500
    r = engine.extract_final_paths(model, e, e.n_returns - 1,
                                   max_seeds=1024)
    assert r.get("valid?") is True, r
    assert "engine-disagreement" in r


def test_indecisive_recheck_keeps_device_verdict(monkeypatch):
    """When the bounded recheck cannot decide (budget exhausted), the
    device verdict stands, tagged — never silently flipped."""
    from jepsen_tpu.histories import rand_register_history
    from jepsen_tpu.models import CASRegister

    model = CASRegister()
    h = rand_register_history(n_ops=60, n_processes=4, crash_p=0.01,
                              fail_p=0.05, seed=21)
    e = enc_mod.encode(model, h)
    monkeypatch.setattr(engine, "DISAGREEMENT_RECHECK_MAX_STATES", 1)
    r = engine.extract_final_paths(model, e, e.n_returns - 1)
    assert "valid?" not in r           # verdict untouched
    assert "recheck indecisive" in r.get("final-paths-note", ""), r

def test_encode_snapshot_interval_fill_matches_naive_oracle():
    """encode()'s interval-fill snapshot construction vs a naive
    per-return reconstruction (the straightforward O(R*C) formulation):
    every column of every row must match, including slot reuse after
    returns and crashed calls holding slots to the end."""
    from jepsen_tpu.histories import (adversarial_register_history,
                                      rand_fifo_history,
                                      rand_register_history)
    from jepsen_tpu.models import CASRegister, FIFOQueue

    cases = [(CASRegister(), rand_register_history(
                 n_ops=150, n_processes=8, n_values=4, crash_p=0.05,
                 fail_p=0.08, seed=s)) for s in range(4)]
    cases += [(CASRegister(), adversarial_register_history(
                  n_ops=80, k_crashed=9, seed=1))]
    cases += [(FIFOQueue(), rand_fifo_history(
                  n_ops=40, n_processes=5, n_values=3, crash_p=0.1,
                  seed=2))]
    for model, h in cases:
        e = enc_mod.encode(model, h)
        spec = e.spec
        packed = [spec.encode_call(c.f, c.value, c.result, c.crashed)
                  for c in e.calls]
        # naive reconstruction: replay events, snapshot before returns
        import heapq as hq
        events = []
        for c in e.calls:
            events.append((c.invoke_index, 0, c.index))
            if not c.crashed:
                events.append((c.complete_index, 1, c.index))
        events.sort()
        free, n_slots, slot_of, occupant = [], 0, {}, {}
        r = 0
        for _, kind, cid in events:
            if kind == 0:
                s = hq.heappop(free) if free else n_slots
                if s == n_slots:
                    n_slots += 1
                slot_of[cid] = s
                occupant[s] = cid
            else:
                for s in range(e.slot_f.shape[1]):
                    if s in occupant:
                        pk = packed[occupant[s]]
                        assert e.slot_occ[r, s], (r, s)
                        assert e.slot_f[r, s] == pk[0]
                        assert e.slot_a0[r, s] == pk[1]
                        assert e.slot_a1[r, s] == pk[2]
                        assert e.slot_wild[r, s] == pk[3]
                    else:
                        assert not e.slot_occ[r, s], (r, s)
                        assert e.slot_f[r, s] == -1
                assert e.ev_slot[r] == slot_of[cid]
                assert e.ret_call[r] == cid
                r += 1
                s = slot_of[cid]
                del occupant[s]
                hq.heappush(free, s)
        assert r == e.n_returns
