"""Clock, faketime, combined-package, and membership nemeses
(reference behaviors: nemesis/time.clj, faketime.clj,
nemesis/combined.clj, nemesis/membership.clj)."""

from __future__ import annotations

import os
import re
import subprocess
from pathlib import Path

import pytest

from jepsen_tpu import control as c
from jepsen_tpu import db as _db
from jepsen_tpu import faketime
from jepsen_tpu import generator as gen
from jepsen_tpu import net
from jepsen_tpu import nemesis as n
from jepsen_tpu.history import Op
from jepsen_tpu.nemesis import combined, membership
from jepsen_tpu.nemesis import time as nt


# --------------------------------------------------------- fake remote


class ScriptedRemote(c.Remote):
    """Records every command; answers clock queries with a scripted
    per-host offset so the clock nemesis sees believable node clocks."""

    def __init__(self, log, offsets):
        self.log = log          # shared list of (host, cmd)
        self.offsets = offsets  # shared dict host -> seconds of skew
        self.host = None

    def connect(self, conn_spec):
        r = ScriptedRemote(self.log, self.offsets)
        r.host = conn_spec.get("host")
        return r

    def disconnect(self):
        pass

    def execute(self, ctx, cmd):
        import time as _t
        self.log.append((self.host, cmd))
        if "date +%s.%N" in cmd:
            now = _t.time() + self.offsets.get(self.host, 0.0)
            return c.Result(cmd, 0, f"{now:.9f}", "")
        m = re.search(r"bump-time (-?\d+)$", cmd)
        if m:
            delta = int(m.group(1)) / 1000.0
            self.offsets[self.host] = self.offsets.get(self.host, 0) + delta
            now = _t.time() + self.offsets[self.host]
            return c.Result(cmd, 0, f"{now:.6f}", "")
        if "ntpdate" in cmd:
            self.offsets[self.host] = 0.0
            return c.Result(cmd, 0, "", "")
        return c.Result(cmd, 0, "", "")

    def upload(self, local_paths, remote_path):
        self.log.append((self.host, f"UPLOAD {local_paths} {remote_path}"))

    def download(self, remote_paths, local_path):
        pass


def scripted_test(nodes=("n1", "n2", "n3")):
    log, offsets = [], {}
    return {"nodes": list(nodes),
            "remote": ScriptedRemote(log, offsets),
            "net": net.mem()}, log, offsets


# -------------------------------------------------------- C helpers


def test_clock_helper_sources_compile(tmp_path):
    src_dir = Path(nt.RESOURCE_DIR)
    for name in ("bump-time", "strobe-time", "strobe-time-experiment"):
        binary = tmp_path / name
        subprocess.run(["gcc", "-O2", "-o", str(binary),
                        str(src_dir / f"{name}.c")], check=True)
        # Wrong usage exits 1 without touching the clock.
        r = subprocess.run([str(binary)], capture_output=True)
        assert r.returncode == 1
        assert b"usage" in r.stderr


def test_strobe_experiment_phase_locked_ticks(tmp_path):
    """The experiment variant's flips are phase-locked to the monotonic
    clock: with delta=0 (clock untouched in effect) the flip count over
    a run equals duration/period exactly — a sleep(period) loop loses
    ticks to per-iteration overhead; the anchor-based schedule must
    not."""
    src = Path(nt.RESOURCE_DIR) / "strobe-time-experiment.c"
    binary = tmp_path / "strobe-time-experiment"
    subprocess.run(["gcc", "-O2", "-o", str(binary), str(src)],
                   check=True)
    # even with delta=0 each flip re-writes the wall clock (losing the
    # syscall-gap microseconds): only exercise it on a disposable box —
    # a container, or an explicit opt-in — never silently on a
    # developer host where concurrent processes may rely on clock
    # monotonicity
    disposable = (os.path.exists("/.dockerenv")
                  or os.path.exists("/run/.containerenv")
                  or os.environ.get("JEPSEN_CLOCK_TESTS") == "1")
    if not disposable:
        pytest.skip("clock-touching test: container or "
                    "JEPSEN_CLOCK_TESTS=1 only")
    r = subprocess.run([str(binary), "0", "20", "0.5"],
                       capture_output=True, timeout=30)
    if r.returncode == 2:
        pytest.skip("no clock privileges in this environment")
    assert r.returncode == 0, r.stderr
    m = re.search(rb"(\d+) flips", r.stderr)
    assert m, r.stderr
    assert int(m.group(1)) == 25   # 0.5s / 20ms, no drift losses


# ------------------------------------------------------ clock nemesis


def test_clock_nemesis_setup_installs_tools():
    test, log, _ = scripted_test()
    nem = nt.clock_nemesis().setup(test)
    uploads = [cmd for _, cmd in log if cmd.startswith("UPLOAD")]
    # Both C sources uploaded to every node.
    assert len(uploads) == 2 * len(test["nodes"])
    gcc_runs = [cmd for _, cmd in log if "gcc" in cmd]
    assert len(gcc_runs) == 2 * len(test["nodes"])
    nem.teardown(test)


def test_clock_nemesis_bump_and_offsets():
    test, log, offsets = scripted_test()
    nem = nt.clock_nemesis().setup(test)
    op = Op({"type": "info", "f": "bump",
             "value": {"n1": 5000, "n2": -3000}})
    out = nem.invoke(test, op)
    assert out["type"] == "info"
    co = out["clock-offsets"]
    assert set(co) == {"n1", "n2"}
    assert co["n1"] == pytest.approx(5.0, abs=0.5)
    assert co["n2"] == pytest.approx(-3.0, abs=0.5)

    check = nem.invoke(test, Op({"type": "info", "f": "check-offsets"}))
    assert set(check["clock-offsets"]) == {"n1", "n2", "n3"}

    reset = nem.invoke(test, Op({"type": "info", "f": "reset",
                                 "value": ["n1", "n2"]}))
    assert reset["clock-offsets"]["n1"] == pytest.approx(0.0, abs=0.5)


def test_clock_gen_schedule():
    test, _, _ = scripted_test()
    test["concurrency"] = 2
    ctx = gen.context(test)
    with gen.fixed_rand(7):
        g = nt.clock_gen()
        res = gen.gen_op(g, test, ctx)
        op, g = res
        # Always opens with check-offsets (nemesis/time.clj:192-198).
        assert op["f"] == "check-offsets"
        event = Op(dict(op, type="info"))
        g = gen.gen_update(g, test, ctx, event)
        fs = set()
        for _ in range(30):
            res = gen.gen_op(g, test, ctx)
            if res is None:
                break
            op, g = res
            if op is gen.PENDING:
                break
            fs.add(op["f"])
            if op["f"] == "bump":
                for delta in op["value"].values():
                    assert 4 <= abs(delta) <= 2 ** 18 * 4
            if op["f"] == "strobe":
                for spec in op["value"].values():
                    assert spec["period"] >= 1
                    assert 0 <= spec["duration"] <= 32
        assert fs <= {"reset", "bump", "strobe"}
        assert len(fs) >= 2


# ----------------------------------------------------------- faketime


def test_faketime_script():
    s = faketime.script("/opt/db/bin/db", -30, 2.0)
    assert s.startswith("#!/bin/bash\n")
    assert 'faketime -m -f "-30s x2.0" /opt/db/bin/db "$@"' in s
    s2 = faketime.script("/bin/x", 5, 0.5)
    assert '"+5s x0.5"' in s2


def test_faketime_rand_factor_bounds():
    with gen.fixed_rand(3):
        for _ in range(100):
            rate = faketime.rand_factor(2.5)
            mx = 2 / (1 + 1 / 2.5)
            assert mx / 2.5 <= rate <= mx
            # fastest/slowest possible draw ratio is exactly the factor


def test_faketime_wrap_unwrap(tmp_path):
    # Run against the real local filesystem via LocalRemote.
    binary = tmp_path / "victim"
    binary.write_text("#!/bin/bash\necho real\n")
    binary.chmod(0o755)
    remote = c.LocalRemote().connect({})
    with c.on_host(remote, "local"):
        faketime.wrap(str(binary), 10, 1.5)
        wrapped = binary.read_text()
        assert "faketime" in wrapped
        assert (tmp_path / "victim.no-faketime").exists()
        # Idempotent: wrapping again keeps the original.
        faketime.wrap(str(binary), 10, 1.5)
        assert "real" in (tmp_path / "victim.no-faketime").read_text()
        faketime.unwrap(str(binary))
        assert binary.read_text().endswith("echo real\n")
        assert not (tmp_path / "victim.no-faketime").exists()


# ----------------------------------------------------- combined package


class FakeDB(_db.DB, _db.Process, _db.Pause, _db.Primary):
    def __init__(self):
        self.events = []

    def setup(self, test, node):
        pass

    def teardown(self, test, node):
        pass

    def start(self, test, node):
        self.events.append(("start", node))
        return "started"

    def kill(self, test, node):
        self.events.append(("kill", node))
        return "killed"

    def pause(self, test, node):
        self.events.append(("pause", node))
        return "paused"

    def resume(self, test, node):
        self.events.append(("resume", node))
        return "resumed"

    def primaries(self, test):
        return [test["nodes"][0]]


def test_db_nodes_specs():
    test = {"nodes": ["a", "b", "c", "d", "e"]}
    db = FakeDB()
    with gen.fixed_rand(5):
        assert combined.db_nodes(test, db, "all") == test["nodes"]
        assert len(combined.db_nodes(test, db, "one")) == 1
        assert len(combined.db_nodes(test, db, "minority")) == 2
        assert len(combined.db_nodes(test, db, "majority")) == 3
        assert len(combined.db_nodes(test, db, "minority-third")) == 1
        assert combined.db_nodes(test, db, ["a", "b"]) == ["a", "b"]
        sub = combined.db_nodes(test, db, None)
        assert 1 <= len(sub) <= 5
        prim = combined.db_nodes(test, db, "primaries")
        assert prim == ["a"]
    assert "primaries" in combined.node_specs(db)


def test_grudge_specs():
    test = {"nodes": ["a", "b", "c", "d", "e"]}
    db = FakeDB()
    with gen.fixed_rand(5):
        g1 = combined.grudge(test, db, "one")
        # Exactly one isolated node dropping the other four.
        isolated = [k for k, v in g1.items() if len(v) == 4]
        assert len(isolated) == 1
        g2 = combined.grudge(test, db, "majority")
        sizes = sorted({len(v) for v in g2.values()})
        assert sizes == [2, 3]
        g3 = combined.grudge(test, db, "majorities-ring")
        assert set(g3) == set(test["nodes"])
        g4 = combined.grudge(test, db, "primaries")
        assert set(g4["a"]) == {"b", "c", "d", "e"}
        # None isolates a random proper nonempty subset.
        g5 = combined.grudge(test, db, None)
        assert g5 and all(v for v in g5.values())


def test_empty_faults_means_no_packages():
    assert combined.nemesis_packages({"db": FakeDB(), "faults": []}) == []


def test_nemesis_package_composition():
    db = FakeDB()
    test, log, _ = scripted_test(("a", "b", "c"))
    test["db"] = db
    pkg = combined.nemesis_package(
        {"db": db, "faults": ["partition", "kill", "pause"], "interval": 1})
    nem = pkg["nemesis"].setup(test)
    fs = nem.fs()
    assert {"start-partition", "stop-partition", "start", "kill",
            "pause", "resume"} <= fs
    assert pkg["final_generator"]

    # Partition ops route through to the MemNet.
    out = nem.invoke(test, Op({"type": "info", "f": "start-partition",
                               "value": "majority"}))
    assert out["f"] == "start-partition"
    assert test["net"].partitioned()
    out = nem.invoke(test, Op({"type": "info", "f": "stop-partition"}))
    assert not test["net"].partitioned()

    # Kill ops hit the DB on the right nodes.
    with gen.fixed_rand(1):
        out = nem.invoke(test, Op({"type": "info", "f": "kill",
                                   "value": "all"}))
    assert sorted(n_ for f, n_ in db.events if f == "kill") == ["a", "b", "c"]
    assert set(out["value"].values()) == {"killed"}
    nem.teardown(test)

    # perf legend covers each package.
    names = {spec["name"] for spec in pkg["perf"]}
    assert {"partition", "kill", "pause"} <= names


def test_clock_package_renames_fs():
    db = FakeDB()
    pkg = combined.clock_package({"db": db, "faults": {"clock"},
                                  "interval": 1})
    assert pkg["nemesis"].fs() == {"reset-clock", "check-clock-offsets",
                                   "strobe-clock", "bump-clock"}
    test, _, _ = scripted_test(("a", "b"))
    nem = pkg["nemesis"].setup(test)
    out = nem.invoke(test, Op({"type": "info", "f": "bump-clock",
                               "value": {"a": 1000}}))
    assert out["f"] == "bump-clock"
    assert out["clock-offsets"]["a"] == pytest.approx(1.0, abs=0.5)


# --------------------------------------------------------- membership


class FakeClusterState(membership.State):
    """A scripted membership state machine over an in-memory cluster.
    The cluster's actual member set lives in `actual`; node views lag
    behind until the poller refreshes them."""

    def __init__(self, actual, plan):
        self.actual = actual      # {"members": set}
        self.plan = plan          # list of ("add-node"|"remove-node", n)
        self.node_views = None
        self.view = None
        self.pending = None

    def node_view(self, test, node):
        return frozenset(self.actual["members"])

    def merge_views(self, test):
        views = list((self.node_views or {}).values())
        if not views:
            return None
        return frozenset().union(*views)

    def fs(self):
        return {"add-node", "remove-node"}

    def op(self, test):
        if self.pending:
            return "pending"  # one change at a time
        if not self.plan:
            return None
        f, node = self.plan[0]
        return {"type": "info", "f": f, "value": node}

    def invoke(self, test, op):
        f, node = op["f"], op["value"]
        if f == "add-node":
            self.actual["members"].add(node)
        else:
            self.actual["members"].discard(node)
        self.plan.pop(0)
        done = Op(op)
        done["type"] = "info"
        return done

    def resolve_op(self, test, op_pair):
        inv = op_pair[0]
        node, f = inv["value"], inv["f"]
        view = self.view or frozenset()
        applied = (node in view) if f == "add-node" else (node not in view)
        return self if applied else None


def test_membership_nemesis_lifecycle():
    actual = {"members": {"n1", "n2", "n3"}}
    state = FakeClusterState(actual, [("add-node", "n4"),
                                      ("remove-node", "n1")])
    test = {"nodes": ["n1", "n2", "n3"], "concurrency": 2}
    pkg = membership.package(
        {"faults": {"membership"}, "interval": 0,
         "membership": {"state": state, "node_view_interval": 0.05}})
    assert pkg is not None
    nem = pkg["nemesis"].setup(test)
    try:
        ctx = gen.context(test)
        g = membership.MembershipGenerator(nem)

        op, g = g.op(test, ctx)
        assert op["f"] == "add-node" and op["value"] == "n4"
        done = nem.invoke(test, op)
        assert done["type"] == "info"

        # Pollers refresh views; the pending op resolves once the view
        # reflects the addition.
        import time as _t
        deadline = _t.time() + 5
        while _t.time() < deadline and nem.state.pending:
            _t.sleep(0.05)
        assert not nem.state.pending
        assert "n4" in nem.state.view

        op, g = g.op(test, ctx)
        assert op["f"] == "remove-node" and op["value"] == "n1"
        nem.invoke(test, op)
        deadline = _t.time() + 5
        while _t.time() < deadline and nem.state.pending:
            _t.sleep(0.05)
        assert not nem.state.pending
        assert "n1" not in nem.state.view

        # Plan exhausted: generator is done.
        assert g.op(test, ctx) is None
    finally:
        nem.teardown(test)
