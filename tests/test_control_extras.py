"""Reconnect wrapper, control.net helpers, OS variants, report, codec
(reference: reconnect.clj, control/net.clj, os/centos.clj, report.clj,
codec.clj)."""

from __future__ import annotations

import threading

import pytest

from jepsen_tpu import codec, report
from jepsen_tpu import control as c
from jepsen_tpu.control import net as cnet
from jepsen_tpu.control import reconnect


# ----------------------------------------------------------- reconnect


class Conn:
    seq = 0

    def __init__(self):
        Conn.seq += 1
        self.id = Conn.seq
        self.closed = False


def test_wrapper_open_close_reopen():
    opened, closed = [], []

    def op():
        conn = Conn()
        opened.append(conn)
        return conn

    def cl(conn):
        conn.closed = True
        closed.append(conn)

    w = reconnect.wrapper(op, cl, name="db")
    assert w.conn() is None
    w.open()
    c1 = w.conn()
    assert c1 is not None
    w.open()  # no-op when already open (reconnect.clj:54-66)
    assert w.conn() is c1
    w.reopen()
    c2 = w.conn()
    assert c2 is not c1 and c1.closed
    w.close()
    assert w.conn() is None and c2.closed


def test_wrapper_with_conn_reopens_on_error():
    def op():
        return Conn()

    def cl(conn):
        conn.closed = True

    w = reconnect.wrapper(op, cl).open()
    c1 = w.conn()
    with w.with_conn() as conn:
        assert conn is c1
    assert w.conn() is c1  # success: same conn kept

    with pytest.raises(ValueError):
        with w.with_conn() as conn:
            raise ValueError("boom")
    # original error propagated AND the conn was replaced
    assert w.conn() is not c1
    assert c1.closed


def test_wrapper_open_returning_none_raises():
    w = reconnect.wrapper(lambda: None, lambda conn: None)
    with pytest.raises(RuntimeError, match="returned None"):
        w.open()


def test_wrapper_concurrent_readers():
    w = reconnect.wrapper(Conn, lambda conn: None).open()
    inside = []
    barrier = threading.Barrier(4, timeout=5)

    def body():
        with w.with_conn() as conn:
            barrier.wait()  # all 4 readers hold the conn at once
            inside.append(conn)

    ts = [threading.Thread(target=body) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=5)
    assert len(inside) == 4
    assert len({id(conn) for conn in inside}) == 1


def test_rwlock_writer_blocks_readers():
    lk = reconnect.RWLock()
    order = []
    lk.acquire_write()

    def reader():
        with lk.read():
            order.append("read")

    t = threading.Thread(target=reader)
    t.start()
    t.join(timeout=0.2)
    assert order == []  # reader blocked by writer
    order.append("write-done")
    lk.release_write()
    t.join(timeout=5)
    assert order == ["write-done", "read"]


# ------------------------------------------------------------- net


class NetRemote(c.Remote):
    def __init__(self, responses):
        self.responses = responses
        self.cmds = []

    def connect(self, conn_spec):
        return self

    def execute(self, ctx, cmd):
        self.cmds.append(cmd)
        for pat, out in self.responses.items():
            if pat in cmd:
                if isinstance(out, Exception):
                    return c.Result(cmd, 1, "", str(out))
                return c.Result(cmd, 0, out, "")
        return c.Result(cmd, 0, "", "")

    def upload(self, local_paths, remote_path):
        content = open(local_paths[0]).read() if local_paths else ""
        self.cmds.append(f"UPLOAD {remote_path}: {content!r}")

    def download(self, remote_paths, local_path):
        pass


def test_net_helpers():
    r = NetRemote({
        "hostname -I": "10.0.0.5 172.17.0.1",
        "getent ahosts n2": "10.0.0.6   STREAM n2\n10.0.0.6   DGRAM",
        "echo $SSH_CLIENT": "10.0.0.99 53266 22",
        "ping": RuntimeError("unreachable"),
    })
    with c.on_host(r, "n1"):
        assert cnet.local_ip() == "10.0.0.5"
        assert cnet.ip_uncached("n2") == "10.0.0.6"
        assert cnet.control_ip() == "10.0.0.99"
        assert cnet.reachable("n9") is False


def test_net_blank_getent_raises():
    r = NetRemote({"getent ahosts nx": "   "})
    with c.on_host(r, "n1"):
        with pytest.raises(RuntimeError, match="blank getent"):
            cnet.ip_uncached("nx")


# ---------------------------------------------------------- os variants


def test_centos_setup_uses_yum():
    from jepsen_tpu import os as os_mod
    r = NetRemote({"hostname": "n1",
                   "cat /etc/hosts": "127.0.0.1 localhost"})
    with c.on_host(r, "n1"):
        os_mod.centos(["extra-pkg"]).setup({"nodes": ["n1"]}, "n1")
    yum = [cmd for cmd in r.cmds if "yum install" in cmd]
    assert yum and "extra-pkg" in yum[0]
    # loopback line gained the hostname, shipped via upload
    uploads = [cmd for cmd in r.cmds if cmd.startswith("UPLOAD /tmp/jepsen-hosts")]
    assert uploads and "127.0.0.1 localhost n1" in uploads[0]


def test_centos_hostfile_token_match():
    """n1 must still be appended when a superstring token (n10) is
    present; % sequences must survive the round-trip."""
    from jepsen_tpu import os as os_mod
    r = NetRemote({"hostname": "n1",
                   "cat /etc/hosts":
                       "127.0.0.1 localhost n10\nfe80::1%eth0 ipv6host"})
    with c.on_host(r, "n1"):
        os_mod.centos()._hostfile_loopback()
    up = [cmd for cmd in r.cmds if cmd.startswith("UPLOAD /tmp/jepsen-hosts")][0]
    assert "localhost n10 n1" in up
    assert "fe80::1%eth0" in up


def test_ubuntu_is_debian():
    from jepsen_tpu import os as os_mod
    assert isinstance(os_mod.ubuntu(), os_mod.Debian)


# --------------------------------------------------------- report/codec


def test_report_to(tmp_path):
    path = str(tmp_path / "sub" / "report.txt")
    with report.to(path):
        print("all is well")
    assert open(path).read() == "all is well\n"


def test_codec_roundtrip():
    for v in (None, 42, "hi", ["a", 1, None], {"k": [1, 2]},
              {"nested": {"deep": True}}):
        assert codec.decode(codec.encode(v)) == v
    assert codec.encode(None) == b""
    assert codec.decode(b"") is None
    assert codec.decode(None) is None
