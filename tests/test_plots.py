"""Perf graphs, timeline HTML, clock plot, and linear.svg rendering
(reference: checker/perf.clj, checker/timeline.clj, checker/clock.clj,
knossos.linear.report; unit-test style after
test/jepsen/perf_test.clj — synthetic histories exercise plotting)."""

from __future__ import annotations

import xml.etree.ElementTree as ET

import pytest

from jepsen_tpu import store as store_mod
import jepsen_tpu.checker.clock as clock
import jepsen_tpu.checker.perf as perf
from jepsen_tpu.checker import linear_report, plot, timeline
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.history import Op
from jepsen_tpu.models import CASRegister


def synthetic_history(n_ops=400, n_procs=5, seed=11):
    """ok/fail/info mix with latencies and two nemesis windows."""
    import random
    r = random.Random(seed)
    h, t = [], 0
    for i in range(n_ops):
        p = i % n_procs
        f = r.choice(["read", "write", "cas"])
        t += r.randint(1_000_000, 30_000_000)
        inv_t = t
        h.append(Op({"index": len(h), "time": inv_t, "process": p,
                     "type": "invoke", "f": f, "value": i}))
        t += r.randint(500_000, 200_000_000)
        typ = r.choices(["ok", "fail", "info"], weights=[8, 1, 1])[0]
        h.append(Op({"index": len(h), "time": t, "process": p,
                     "type": typ, "f": f, "value": i}))
    # nemesis activity: two partition windows
    dur = t // 5
    for k in range(2):
        s = dur * (1 + 2 * k)
        h.append(Op({"index": len(h), "time": s, "process": "nemesis",
                     "type": "info", "f": "start", "value": "cut"}))
        h.append(Op({"index": len(h), "time": s + dur, "process": "nemesis",
                     "type": "info", "f": "stop", "value": "healed"}))
    return sorted(h, key=lambda o: o["time"])


def _store(tmp_path, name="plots"):
    return store_mod.Store(name, base_dir=str(tmp_path))


def _assert_svg(path):
    assert path and path.endswith(".svg")
    root = ET.parse(path).getroot()
    assert root.tag.endswith("svg")
    return ET.tostring(root, encoding="unicode")


# ----------------------------------------------------------- plot core


def test_buckets_and_quantiles():
    assert plot.bucket_time(10, 7) == 5.0
    assert plot.bucket_time(10, 17) == 15.0
    assert plot.buckets(10, 35) == [5.0, 15.0, 25.0, 35.0]
    q = plot.quantiles([0.5, 1], [1, 2, 3, 4])
    assert q[1] == 4 and q[0.5] == 3
    lq = plot.latencies_to_quantiles(10, [0.5], [[1, 5], [2, 7], [12, 9]])
    assert lq[0.5] == [[5.0, 7], [15.0, 9]]


def test_broaden_range():
    assert plot.broaden_range((5, 5)) == (4, 6)
    lo, hi = plot.broaden_range((0.3, 9.7))
    assert lo <= 0.3 and hi >= 9.7


def test_with_range_raises_no_points():
    with pytest.raises(plot.NoPoints):
        plot.with_range({"series": [{"data": []}]})


def test_nemesis_activity_partitions_ops():
    h = synthetic_history()
    specs = [{"name": "partition", "color": "#E9DCA0",
              "start": {"start"}, "stop": {"stop"}}]
    act = plot.nemesis_activity(specs, h)
    assert len(act) == 1
    assert len(act[0]["intervals"]) == 2
    assert all(b is not None for _a, b in act[0]["intervals"])


# ----------------------------------------------------------- perf graphs


def test_point_graph_renders(tmp_path):
    h = synthetic_history()
    test = {"name": "t", "store": _store(tmp_path)}
    path = perf.point_graph(test, h)
    svg = _assert_svg(path)
    assert "Latency (ms)" in svg
    # all three completion types appear in the legend
    for t in ("ok", "fail", "info"):
        assert t in svg


def test_quantiles_graph_renders(tmp_path):
    h = synthetic_history()
    test = {"name": "t", "store": _store(tmp_path)}
    path = perf.quantiles_graph(test, h)
    svg = _assert_svg(path)
    assert "0.95" in svg and "0.99" in svg


def test_rate_graph_renders(tmp_path):
    h = synthetic_history()
    test = {"name": "t", "store": _store(tmp_path)}
    path = perf.rate_graph(test, h)
    svg = _assert_svg(path)
    assert "Throughput (hz)" in svg


def test_perf_checker_composes(tmp_path):
    h = synthetic_history()
    test = {"name": "t", "store": _store(tmp_path),
            "plot": {"nemeses": [{"name": "partition", "color": "#E9DCA0",
                                  "start": {"start"}, "stop": {"stop"}}]}}
    res = perf.perf().check(test, h)
    assert res["valid?"] is True
    for k in ("latency-graph", "latency-quantiles-graph", "rate-graph"):
        svg = _assert_svg(res[k])
        assert "partition" in svg  # nemesis legend present


def test_perf_empty_history_is_valid():
    res = perf.perf().check({"name": "t"}, [])
    assert res["valid?"] is True
    assert res["latency-graph"] is None


# ------------------------------------------------------------- timeline


def test_timeline_html(tmp_path):
    h = synthetic_history(n_ops=40)
    test = {"name": "t", "store": _store(tmp_path)}
    res = timeline.html().check(test, h)
    assert res["valid?"] is True
    doc = open(res["timeline"]).read()
    assert "<style>" in doc
    assert doc.count('class="op ') >= 40
    assert 'class="op ok"' in doc
    # crashed/unmatched infos are still rendered
    assert 'class="op info"' in doc


def test_timeline_pairs_crashed_ops():
    h = [Op({"index": 0, "time": 0, "process": 0, "type": "invoke",
             "f": "w", "value": 1}),
         Op({"index": 1, "time": 5, "process": "nemesis", "type": "info",
             "f": "start", "value": None}),
         Op({"index": 2, "time": 9, "process": 0, "type": "info",
             "f": "w", "value": 1})]
    ps = timeline.pairs(h)
    # nemesis info stands alone; process-0 invoke pairs with its crash
    assert [len(p) for p in ps] == [1, 2]


# ------------------------------------------------------------- clock


def test_clock_plot(tmp_path):
    h = [Op({"index": 0, "time": 1_000_000_000, "process": "nemesis",
             "type": "info", "f": "check-offsets",
             "clock-offsets": {"n1": 0.0, "n2": 0.1}}),
         Op({"index": 1, "time": 5_000_000_000, "process": "nemesis",
             "type": "info", "f": "bump",
             "clock-offsets": {"n1": 30.0, "n2": 0.1}}),
         Op({"index": 2, "time": 9_000_000_000, "process": "nemesis",
             "type": "info", "f": "reset",
             "clock-offsets": {"n1": 0.0, "n2": 0.0}})]
    test = {"name": "t", "store": _store(tmp_path)}
    res = clock.clock_plot().check(test, h)
    assert res["valid?"] is True
    svg = _assert_svg(res["clock-skew-graph"])
    assert "Skew (s)" in svg and "n1" in svg


def test_clock_plot_no_offsets_ok():
    res = clock.clock_plot().check({"name": "t"}, synthetic_history(20))
    assert res["valid?"] is True
    assert res["clock-skew-graph"] is None


def test_short_node_names():
    assert clock.short_node_names(
        ["n1.db.local", "n2.db.local"]) == ["n1", "n2"]
    assert clock.short_node_names(["a", "b"]) == ["a", "b"]
    assert clock.short_node_names(["only.example.com"]) \
        == ["only.example.com"]


# ----------------------------------------------------- linear.svg


def _invalid_register_history():
    return [Op({"index": 0, "time": 0, "process": 0, "type": "invoke",
                "f": "write", "value": 1}),
            Op({"index": 1, "time": 10, "process": 0, "type": "ok",
                "f": "write", "value": 1}),
            Op({"index": 2, "time": 20, "process": 1, "type": "invoke",
                "f": "read", "value": None}),
            Op({"index": 3, "time": 30, "process": 1, "type": "ok",
                "f": "read", "value": 2})]


def test_render_analysis_highlights_counterexample():
    h = _invalid_register_history()
    analysis = {"valid?": False,
                "op": {"index": 2, "f": "read", "value": 2,
                       "process": 1},
                "final-paths": [[{"op": dict(h[0]), "model": "1"},
                                 {"op": dict(h[2]), "model": "1"}]]}
    svg = linear_report.render_analysis(h, analysis)
    assert "#d00000" in svg              # counterexample outline
    assert "No legal linearization" in svg
    assert "process 0" in svg and "process 1" in svg


def test_linearizable_failure_writes_linear_svg(tmp_path):
    test = {"name": "t", "store": _store(tmp_path)}
    chk = linearizable(CASRegister(), algorithm="wgl")
    res = chk.check(test, _invalid_register_history())
    assert res["valid?"] is False
    path = test["store"].path("linear.svg")
    _assert_svg(path)
