"""The perf A/B harness's hardware-independent cost prior.

tools/perf_ab.py decides the closure defaults (while/fori/pallas) from
MEASURED ratios on the real chip; the trace-time XLA cost_analysis
prior (bitdense.cost_analysis_encoded/_batch) must be populated on any
backend — including CPU — so the decision has an analytical anchor
during hardware-dark rounds and a cross-check once measured.
"""

import json
import os
import subprocess
import sys

import pytest

from jepsen_tpu.histories import (adversarial_register_history,
                                  rand_register_history)
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import bitdense, encode as enc_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _adv_encoded(n_ops=120, k=8):
    h = adversarial_register_history(n_ops=n_ops, k_crashed=k, seed=7)
    return enc_mod.encode(CASRegister(), h)


def test_cost_analysis_encoded_populated_on_cpu():
    e = _adv_encoded()
    cw = bitdense.cost_analysis_encoded(e, closure_mode="while")
    cf = bitdense.cost_analysis_encoded(e, closure_mode="fori")
    for c in (cw, cf):
        assert c["flops"] > 0, c
        assert c["bytes_accessed"] > 0, c
    # XLA's cost model counts each loop body once (trip counts are
    # data-dependent), so while and fori — same expansion body, only
    # the loop carried convergence test differs — must land close:
    # the prior ranks per-iteration variant cost, not totals
    assert abs(cf["flops"] - cw["flops"]) < 0.2 * cw["flops"], (cw, cf)


def test_cost_analysis_scales_with_config_width():
    # trip counts don't show (loop bodies count once), but the
    # expansion body's own tensors scale with the config-word width W
    # = 2^k/32: +2 crashed writes quadruples W and must dominate
    narrow = bitdense.cost_analysis_encoded(_adv_encoded(k=8))
    wide = bitdense.cost_analysis_encoded(_adv_encoded(k=10))
    assert wide["flops"] > 2 * narrow["flops"], (narrow, wide)


def test_cost_analysis_pallas_downgrades_like_execution_paths():
    """use_pallas=True on a kernel-unsupported shape must downgrade
    through the shared gate (as check_encoded_bitdense does), not
    raise a bare kernel assert; the 'program' field tells the caller
    what was actually costed."""
    e = _adv_encoded(k=2)    # W=1 word, far below kernel support
    c = bitdense.cost_analysis_encoded(e, use_pallas=True)
    assert c["program"] == "xla-while", c
    assert c["flops"] > 0, c


def test_cost_analysis_batch_populated_on_cpu():
    encs = [enc_mod.encode(
        CASRegister(),
        rand_register_history(n_ops=30, n_processes=4, crash_p=0.01,
                              fail_p=0.05, seed=100 + k))
        for k in range(4)]
    c = bitdense.cost_analysis_batch(encs, closure_mode="while")
    assert c["flops"] > 0 and c["bytes_accessed"] > 0, c


def test_disagreeing_flags_wrong_variants_only():
    """The correctness gate compares full results (verdict +
    counterexample) against the while baseline, ignoring the closure
    label, and names exactly the variants that differ."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_ab", os.path.join(REPO, "tools", "perf_ab.py"))
    perf_ab = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_ab)

    ok = {"valid?": True, "engine": "bitdense", "closure": "xla-while"}
    same = dict(ok, closure="xla-fori")
    wrong = dict(ok, closure="pallas")
    wrong["valid?"] = False
    assert perf_ab._disagreeing(
        {"while": [ok], "fori": [same], "pallas": [dict(ok)]}) == set()
    assert perf_ab._disagreeing(
        {"while": [ok], "fori": [same], "pallas": [wrong]}) == {"pallas"}
    # EVERY run counts: one early wrong answer flags even when the
    # final run agrees (nondeterministic kernels must not slip through)
    assert perf_ab._disagreeing(
        {"while": [ok, ok], "fori": [wrong, same]}) == {"fori"}
    # a nondeterministic BASELINE flags itself (vetoes everything)
    assert perf_ab._disagreeing(
        {"while": [ok, wrong], "fori": [same]}) == {"while"}
    # batch form: run lists hold per-key result lists
    assert perf_ab._disagreeing(
        {"while": [[ok, ok]], "fori": [[same, wrong]]}) == {"fori"}


def test_perf_ab_dedupe_unknown_strategy_raises():
    """A typo in PERF_AB_DEDUPE must abort the harness with the valid
    set listed — a silently-skipped 'hash-palas' would read as
    measured-and-lost on the chip session the flip decision waits on.
    The check runs at module import, before any backend probe, so the
    failure is fast and backend-independent."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"PERF_AB_DEDUPE": "sort,hash-palas",
                "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_ab.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode != 0, r.stdout[-500:]
    assert "unknown strategy" in r.stderr, r.stderr[-500:]
    assert "hash-palas" in r.stderr
    # the message must NAME the valid set, so the fix is in the error
    assert "sort,hash,hash-pallas,hash-packed" in r.stderr, \
        r.stderr[-500:]


def _gate_coverage_for(k: int, n_ops: int = 1000):
    """The host-only gate record for one chip-matrix adversarial
    shape, at the capacity tier the perf_ab dedupe block uses
    (1 << (k + 4))."""
    from jepsen_tpu.parallel import sparse_kernels as sk
    e = _adv_encoded(n_ops=n_ops, k=k)
    return sk.gate_coverage(e.n_states, e.state_lo,
                            e.slot_f.shape[1], 1 << (k + 4))


def test_gate_coverage_schema_and_chip_matrix_coverage():
    """Pin the gate_coverage record schema (the evidence line perf_ab
    emits per dedupe shape) AND the ISSUE-11 acceptance claim: for
    every shape in the chip A/B matrix ([(1000, 12), (1000, 8)]), the
    would-run decision is "pallas" or "pallas-tiled" — NEVER a
    wholesale "xla-hash" — and the k=12 (L=1000) headline shape that
    previously degraded is admitted. Host-only: no chip, no tracing,
    just the width-aware gate math."""
    for k in (12, 8):
        rec = _gate_coverage_for(k)
        # schema pin: the chip campaign scripts read these fields
        assert set(rec) == {"C", "capacity", "budget", "packable",
                            "state_bits", "packed_width_bits",
                            "would_run", "bytes_per_row"}, rec
        assert set(rec["would_run"]) == {"packed", "unpacked"}
        assert rec["packable"] is True
        assert rec["bytes_per_row"]["unpacked"] == 48
        assert rec["bytes_per_row"]["packed"] < 48
        assert rec["packed_width_bits"] == rec["state_bits"] + rec["C"]
        for layout in ("packed", "unpacked"):
            assert rec["would_run"][layout] in ("pallas",
                                                "pallas-tiled"), \
                (k, layout, rec)
    # k=8 at capacity 4096 fits the fused kernel outright
    assert _gate_coverage_for(8)["would_run"]["packed"] == "pallas"
    # k=12 at capacity 65536 is past whole-event fusion but covered
    # by the tiled closure — the previously-degraded headline shape
    r12 = _gate_coverage_for(12)
    assert r12["would_run"]["packed"] in ("pallas", "pallas-tiled")
    assert r12["would_run"]["packed"] != "xla-hash"


def test_gate_coverage_unpackable_family():
    """A family whose word exceeds 64 bits reports packable=False with
    null packed fields — the overflow-to-unpacked evidence the record
    must carry rather than fabricate."""
    from jepsen_tpu.parallel import sparse_kernels as sk
    rec = sk.gate_coverage(n_states=1 << 30, state_lo=0, C=40, N=1024)
    assert rec["packable"] is False
    assert rec["packed_width_bits"] is None
    assert rec["would_run"]["packed"] is None
    assert rec["would_run"]["unpacked"] in ("pallas", "pallas-tiled")


@pytest.mark.slow
def test_perf_ab_emits_cost_table_on_cpu():
    """Full smoke run of the harness: the aggregated cost_table line
    carries populated while+fori priors (plus static trip counts) for
    every measured shape and precedes the verdict."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"BENCH_SMOKE": "1", "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_ab.py")],
        capture_output=True, text=True, env=env, timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.lstrip().startswith("{")]
    assert [l for l in lines if "shape" in l], lines
    table = next(l for l in lines if "cost_table" in l)["cost_table"]
    assert set(table) == {"single-200", "single-400", "batch"}
    for shape, cost in table.items():
        for variant in ("while", "fori"):
            assert cost[variant].get("flops", 0) > 0, (shape, cost)
            assert cost[variant]["program"] == f"xla-{variant}"
        assert cost["trips"]["scan_events"] > 0, (shape, cost)
        assert cost["trips"]["fori_closure"] > 0, (shape, cost)
    # all variants agreed on every shape (interpret-mode pallas and
    # the packed word included): the correctness gate stays silent
    assert not [l for l in lines if "correctness_mismatch" in l], lines
    # every dedupe shape ships its host-only gate-coverage evidence
    gc = [l for l in lines if "gate_coverage" in l]
    assert gc and all("would_run" in l["gate_coverage"] for l in gc)
    assert "config_pack_verdict" in lines[-1]
    assert "verdict" in lines[-1]


def test_perf_ab_elastic_unknown_arm_raises():
    """PERF_AB_ELASTIC gets the same typo-protection as the other
    selector envs: an unknown arm aborts at import with the valid set
    named, never a silent skip that reads as measured-and-lost."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"PERF_AB_ELASTIC": "steal,reshards",
                "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_ab.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode != 0, r.stdout[-500:]
    assert "unknown arm" in r.stderr, r.stderr[-500:]
    assert "reshards" in r.stderr
    assert "steal,reshard" in r.stderr, r.stderr[-500:]


def _load_perf_ab():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_ab", os.path.join(REPO, "tools", "perf_ab.py"))
    perf_ab = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_ab)
    return perf_ab


@pytest.mark.slow
def test_compile_record_warm_beats_cold_on_chip_matrix():
    """The compile-economics acceptance pin, at CPU scale: for both
    chip-matrix ks (the dedupe A/B's k=8 / k=12 pair, derated to tiny
    op counts), the warm-cache arm serves its FIRST dispatch with zero
    fresh compiles — the registry ledger proves it (compiles == 0,
    preloads >= 1, load_errors == 0), not a timing inference — and
    strictly faster than the cold arm, with a bit-identical verdict
    pin. The population record rides along: canonicalization must
    never *increase* the distinct-program count, and the jittered
    extra_rows here (three lengths, one quantum rung) must shrink it."""
    perf_ab = _load_perf_ab()
    out = perf_ab.compile_record([(200, 8), (200, 6)],
                                 extra_rows=[100, 101, 120])
    assert len(out["records"]) == 2
    for rec in out["records"]:
        assert "cold_error" not in rec and "warm_error" not in rec, rec
        assert "pin_mismatch" not in rec, rec
        assert rec["cold_compiles"] >= 1, rec
        assert rec["warm_compiles"] == 0, rec
        assert rec["warm_preloads"] >= 1, rec
        assert rec["warm_load_errors"] == 0, rec
        assert (rec["warm_first_dispatch_secs"]
                < rec["cold_first_dispatch_secs"]), rec
    pop = out["population"]
    assert pop["canon"] <= pop["exact"], pop
    assert pop["canon"] < pop["exact"], pop   # 100/101/120 share rungs
    assert pop["canon"] >= 1


def test_perf_ab_compile_invalid_value_raises():
    """PERF_AB_COMPILE gets the same typo-protection as the other
    selector envs: anything but 0/1 aborts at import with the valid
    set named."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"PERF_AB_COMPILE": "yes", "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_ab.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode != 0, r.stdout[-500:]
    assert "PERF_AB_COMPILE" in r.stderr, r.stderr[-500:]
    assert "valid: 0,1" in r.stderr, r.stderr[-500:]
