"""The perf A/B harness's hardware-independent cost prior.

tools/perf_ab.py decides the closure defaults (while/fori/pallas) from
MEASURED ratios on the real chip; the trace-time XLA cost_analysis
prior (bitdense.cost_analysis_encoded/_batch) must be populated on any
backend — including CPU — so the decision has an analytical anchor
during hardware-dark rounds and a cross-check once measured.
"""

import json
import os
import subprocess
import sys

import pytest

from jepsen_tpu.histories import (adversarial_register_history,
                                  rand_register_history)
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import bitdense, encode as enc_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _adv_encoded(n_ops=120, k=8):
    h = adversarial_register_history(n_ops=n_ops, k_crashed=k, seed=7)
    return enc_mod.encode(CASRegister(), h)


def test_cost_analysis_encoded_populated_on_cpu():
    e = _adv_encoded()
    cw = bitdense.cost_analysis_encoded(e, closure_mode="while")
    cf = bitdense.cost_analysis_encoded(e, closure_mode="fori")
    for c in (cw, cf):
        assert c["flops"] > 0, c
        assert c["bytes_accessed"] > 0, c
    # XLA's cost model counts each loop body once (trip counts are
    # data-dependent), so while and fori — same expansion body, only
    # the loop carried convergence test differs — must land close:
    # the prior ranks per-iteration variant cost, not totals
    assert abs(cf["flops"] - cw["flops"]) < 0.2 * cw["flops"], (cw, cf)


def test_cost_analysis_scales_with_config_width():
    # trip counts don't show (loop bodies count once), but the
    # expansion body's own tensors scale with the config-word width W
    # = 2^k/32: +2 crashed writes quadruples W and must dominate
    narrow = bitdense.cost_analysis_encoded(_adv_encoded(k=8))
    wide = bitdense.cost_analysis_encoded(_adv_encoded(k=10))
    assert wide["flops"] > 2 * narrow["flops"], (narrow, wide)


def test_cost_analysis_pallas_downgrades_like_execution_paths():
    """use_pallas=True on a kernel-unsupported shape must downgrade
    through the shared gate (as check_encoded_bitdense does), not
    raise a bare kernel assert; the 'program' field tells the caller
    what was actually costed."""
    e = _adv_encoded(k=2)    # W=1 word, far below kernel support
    c = bitdense.cost_analysis_encoded(e, use_pallas=True)
    assert c["program"] == "xla-while", c
    assert c["flops"] > 0, c


def test_cost_analysis_batch_populated_on_cpu():
    encs = [enc_mod.encode(
        CASRegister(),
        rand_register_history(n_ops=30, n_processes=4, crash_p=0.01,
                              fail_p=0.05, seed=100 + k))
        for k in range(4)]
    c = bitdense.cost_analysis_batch(encs, closure_mode="while")
    assert c["flops"] > 0 and c["bytes_accessed"] > 0, c


def test_disagreeing_flags_wrong_variants_only():
    """The correctness gate compares full results (verdict +
    counterexample) against the while baseline, ignoring the closure
    label, and names exactly the variants that differ."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "perf_ab", os.path.join(REPO, "tools", "perf_ab.py"))
    perf_ab = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_ab)

    ok = {"valid?": True, "engine": "bitdense", "closure": "xla-while"}
    same = dict(ok, closure="xla-fori")
    wrong = dict(ok, closure="pallas")
    wrong["valid?"] = False
    assert perf_ab._disagreeing(
        {"while": [ok], "fori": [same], "pallas": [dict(ok)]}) == set()
    assert perf_ab._disagreeing(
        {"while": [ok], "fori": [same], "pallas": [wrong]}) == {"pallas"}
    # EVERY run counts: one early wrong answer flags even when the
    # final run agrees (nondeterministic kernels must not slip through)
    assert perf_ab._disagreeing(
        {"while": [ok, ok], "fori": [wrong, same]}) == {"fori"}
    # a nondeterministic BASELINE flags itself (vetoes everything)
    assert perf_ab._disagreeing(
        {"while": [ok, wrong], "fori": [same]}) == {"while"}
    # batch form: run lists hold per-key result lists
    assert perf_ab._disagreeing(
        {"while": [[ok, ok]], "fori": [[same, wrong]]}) == {"fori"}


def test_perf_ab_dedupe_unknown_strategy_raises():
    """A typo in PERF_AB_DEDUPE must abort the harness with the valid
    set listed — a silently-skipped 'hash-palas' would read as
    measured-and-lost on the chip session the flip decision waits on.
    The check runs at module import, before any backend probe, so the
    failure is fast and backend-independent."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"PERF_AB_DEDUPE": "sort,hash-palas",
                "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_ab.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode != 0, r.stdout[-500:]
    assert "unknown strategy" in r.stderr, r.stderr[-500:]
    assert "hash-palas" in r.stderr
    # the message must NAME the valid set, so the fix is in the error
    assert "sort,hash,hash-pallas" in r.stderr, r.stderr[-500:]


@pytest.mark.slow
def test_perf_ab_emits_cost_table_on_cpu():
    """Full smoke run of the harness: the aggregated cost_table line
    carries populated while+fori priors (plus static trip counts) for
    every measured shape and precedes the verdict."""
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"BENCH_SMOKE": "1", "JAX_PLATFORMS": "cpu"})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_ab.py")],
        capture_output=True, text=True, env=env, timeout=900)
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.lstrip().startswith("{")]
    assert [l for l in lines if "shape" in l], lines
    table = next(l for l in lines if "cost_table" in l)["cost_table"]
    assert set(table) == {"single-200", "single-400", "batch"}
    for shape, cost in table.items():
        for variant in ("while", "fori"):
            assert cost[variant].get("flops", 0) > 0, (shape, cost)
            assert cost[variant]["program"] == f"xla-{variant}"
        assert cost["trips"]["scan_events"] > 0, (shape, cost)
        assert cost["trips"]["fori_closure"] > 0, (shape, cost)
    # all three variants agreed on every shape (interpret-mode pallas
    # included): the correctness gate must stay silent on a clean run
    assert not [l for l in lines if "correctness_mismatch" in l], lines
    assert "verdict" in lines[-1]
