"""Parity + property suite for the packed configuration word
(JEPSEN_TPU_CONFIG_PACK, ISSUE 11 "VMEM economics").

A configuration historically travels the engines as the (state i32,
mask_lo u32, mask_hi u32) triple; packed, it is
``(state - state_lo) | mask << state_bits`` carried as 1-2 uint32
lanes. Representation must NEVER change results: verdict, failing
op/event, max-frontier, and configs-stepped are pinned identical
across layouts for the packable families, sort and hash dedupe,
serial / batch / sharded / resumable / streamed — clean and
corrupted. Width edges (31/32/33/63/64 bits, the lane boundaries) are
covered by the host round-trip property tests; families past 64 bits
take the overflow-to-unpacked path, tagged, never wrong."""

import os
import unittest.mock as mock
from dataclasses import dataclass

import numpy as np
import pytest

from jepsen_tpu.histories import (corrupt_history, rand_fifo_history,
                                  rand_gset_history, rand_queue_history,
                                  rand_register_history)
from jepsen_tpu.history import History, invoke_op, ok_op
from jepsen_tpu.models import (CASRegister, FIFOQueue, GSet, Mutex,
                               UnorderedQueue)
from jepsen_tpu.parallel import encode as enc_mod, engine

# the five packable families — same generators (and therefore the same
# compiled reference shapes) as tests/test_dedupe.py /
# tests/test_sparse_pallas.py, so only the packed variants compile
# fresh here
FAMILIES = [
    ("cas-register", CASRegister,
     lambda: rand_register_history(n_ops=40, n_processes=5, n_values=3,
                                   crash_p=0.06, fail_p=0.08, seed=31)),
    ("gset", GSet,
     lambda: rand_gset_history(n_ops=36, n_processes=4, n_elements=9,
                               crash_p=0.06, seed=33)),
    ("uqueue", UnorderedQueue,
     lambda: rand_queue_history(n_ops=26, n_processes=4, n_values=3,
                                crash_p=0.06, seed=34)),
    ("fifo", FIFOQueue,
     lambda: rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                               crash_p=0.05, seed=35)),
]

PIN = ("valid?", "op", "fail-event", "max-frontier", "configs-stepped")


def _pin(r):
    return {k: r.get(k) for k in PIN}


# ------------------------------------------------------ layout + math


def test_pack_layout_boundary_widths():
    """Lane-boundary widths: <=32 bits is one lane, 33..64 two lanes,
    65+ (or a state field past one lane) unpackable."""
    # (n_states, C) -> expected (state_bits, lanes) or None
    cases = [
        ((2, 30), (1, 1)),       # 31-bit word
        ((2, 31), (1, 1)),       # 32-bit word: still one lane
        ((2, 32), (1, 2)),       # 33 bits: lane boundary crossed
        ((1 << 32, 31), (32, 2)),   # 63 bits
        ((1 << 32, 32), (32, 2)),   # 64 bits exactly: still packs
        ((1 << 32, 33), None),      # 65 bits: overflow-to-unpacked
        ((1 << 33, 16), None),      # state field past one lane
        ((2, 64), None),            # mask alone past 64 with state
    ]
    for (S, C), want in cases:
        lay = engine.pack_layout(S, -1, C)
        if want is None:
            assert lay is None, (S, C, lay)
        else:
            s_bits, lanes = want
            assert lay == (s_bits, -1), (S, C, lay)
            assert engine.pack_lanes(lay, C) == lanes, (S, C)
    # unknown state space never packs
    assert engine.pack_layout(0, -1, 8) is None
    assert engine.pack_lanes((), 8) == 3


def test_pack_roundtrip_property():
    """Randomized round-trip over (state, mask) WITHIN per-event
    bounds, across the lane-boundary widths: pack_rows_np ->
    unpack_rows_np is the identity."""
    rng = np.random.default_rng(0)
    # (state_bits, C) spanning 31/32/33/63/64-bit words and both
    # mask-lane splits
    for s_bits, C in [(1, 30), (1, 31), (1, 32), (5, 27), (5, 28),
                      (3, 29), (31, 1), (32, 31), (32, 32), (30, 33),
                      (16, 47), (8, 56), (28, 36)]:
        for state_lo in (-1, 0, 7):
            pack = (s_bits, state_lo)
            n = 257
            st = (rng.integers(0, 1 << s_bits, n, dtype=np.int64)
                  + state_lo).astype(np.int32)
            mask = rng.integers(0, 1 << C, n,
                                dtype=np.uint64 if C >= 63 else np.int64
                                ).astype(np.uint64) \
                & np.uint64((1 << C) - 1)
            ml = (mask & np.uint64(0xFFFFFFFF)).astype(np.uint32)
            mh = (mask >> np.uint64(32)).astype(np.uint32)
            rows = engine.pack_rows_np(pack, C, st, ml, mh)
            assert len(rows) == engine.pack_lanes(pack, C), (s_bits, C)
            st2, ml2, mh2 = engine.unpack_rows_np(pack, C, rows)
            np.testing.assert_array_equal(st, st2, err_msg=f"{s_bits},{C}")
            np.testing.assert_array_equal(ml, ml2)
            np.testing.assert_array_equal(mh, mh2)


def test_packed_rep_traced_semantics():
    """The device-side rep agrees with the host pack: states unpack
    exactly, mask-bit tests and the event-bit clear match the
    canonical triple's semantics — under jit, on both 1- and 2-lane
    layouts."""
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(1)
    for s_bits, C in [(5, 12), (5, 40)]:
        pack = (s_bits, -1)
        rep = engine._rep(pack, C)
        n = 64
        st = (rng.integers(0, 1 << s_bits, n) - 1).astype(np.int32)
        mask = rng.integers(0, 1 << C, n, dtype=np.int64).astype(
            np.uint64)
        ml = (mask & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        mh = (mask >> np.uint64(32)).astype(np.uint32)
        rows = tuple(jnp.asarray(r)
                     for r in engine.pack_rows_np(pack, C, st, ml, mh))

        @jax.jit
        def probe(rows, slot):
            bits = rep.event_bits(slot.astype(jnp.uint32))
            has = rep.has_event_bit(rows, bits)
            cleared = rep.clear_event_bit(rows, bits, has)
            return rep.state(rows), rep.mask_test(rows), has, cleared

        slot = np.int32(C - 1)
        st_d, test_d, has_d, cleared = probe(rows, slot)
        np.testing.assert_array_equal(np.asarray(st_d), st)
        want_test = np.stack(
            [(mask >> np.uint64(j)) & np.uint64(1) != 0
             for j in range(C)], axis=1)
        np.testing.assert_array_equal(np.asarray(test_d), want_test)
        np.testing.assert_array_equal(
            np.asarray(has_d), (mask >> np.uint64(int(slot)))
            & np.uint64(1) != 0)
        st3, ml3, mh3 = engine.unpack_rows_np(
            pack, C, [np.asarray(x) for x in cleared])
        want_mask = np.where(np.asarray(has_d),
                             mask & ~(np.uint64(1) << np.uint64(int(slot))),
                             mask)
        np.testing.assert_array_equal(
            ml3.astype(np.uint64) | (mh3.astype(np.uint64) << np.uint64(32)),
            want_mask)
        np.testing.assert_array_equal(st3, st)


@dataclass
class _FakeEnc:
    n_states: int
    state_lo: int
    slot_f: np.ndarray


def test_pack_spec_for_unions_batch_domains():
    """A batch shares ONE layout: the state field covers the union of
    every member's domain; one unpackable member makes the whole
    program unpacked."""
    f = np.zeros((4, 10), np.int32)
    a = _FakeEnc(n_states=16, state_lo=-1, slot_f=f)
    b = _FakeEnc(n_states=100, state_lo=50, slot_f=f)
    pack = engine.pack_spec_for([a, b], 10)
    assert pack
    s_bits, lo = pack
    assert lo == -1 and (1 << s_bits) >= 151  # covers [-1, 150)
    wide = _FakeEnc(n_states=1 << 31, state_lo=0,
                    slot_f=np.zeros((4, 40), np.int32))
    assert engine.pack_spec_for([a, wide], 40) == ()
    assert engine.pack_spec_for([], 10) == ()


# --------------------------------------------------------- env flag


def test_config_pack_env_flag_and_tagging():
    from jepsen_tpu.envflags import EnvFlagError
    h = rand_register_history(n_ops=24, n_processes=3, crash_p=0.0,
                              seed=5)
    e = enc_mod.encode(CASRegister(), h)
    # default off: no tag, byte-identical schema
    r = engine.check_encoded(e, capacity=64, dedupe="hash")
    assert "config-pack" not in r
    with mock.patch.dict(os.environ, {"JEPSEN_TPU_CONFIG_PACK": "1"}):
        rp = engine.check_encoded(e, capacity=64, dedupe="hash")
    assert rp["config-pack"].startswith("packed:")
    assert _pin(rp) == _pin(r)
    with mock.patch.dict(os.environ,
                         {"JEPSEN_TPU_CONFIG_PACK": "yes"}), \
            pytest.raises(EnvFlagError, match="CONFIG_PACK"):
        engine.check_encoded(e, capacity=64, dedupe="hash")


def test_overflow_to_unpacked_path():
    """A family whose word cannot pack (state_bits + C > 64) runs the
    historical triple under config_pack=True — tagged "unpacked",
    results identical, never an error."""
    h = rand_register_history(n_ops=24, n_processes=3, crash_p=0.0,
                              seed=5)
    e = enc_mod.encode(CASRegister(), h)
    ref = engine.check_encoded(e, capacity=64, dedupe="hash")
    with mock.patch.object(engine, "pack_layout",
                           lambda *a, **k: None):
        r = engine.check_encoded(e, capacity=64, dedupe="hash",
                                 config_pack=True)
    assert r["config-pack"] == "unpacked"
    assert _pin(r) == _pin(ref)


# ------------------------------------------------------ parity matrix


@pytest.mark.parametrize("name,Model,gen", FAMILIES,
                         ids=[c[0] for c in FAMILIES])
def test_packed_parity_clean_and_corrupted(name, Model, gen):
    """Serial engine, hash dedupe: packed bit-identical to the
    unpacked XLA hash on every packable family, clean + corrupted."""
    h = gen()
    for variant in (h, corrupt_history(h, seed=7, n_corruptions=2)):
        try:
            e = enc_mod.encode(Model(), variant)
        except enc_mod.EncodeError:
            continue
        ref = engine.check_encoded(e, capacity=128, dedupe="hash")
        r = engine.check_encoded(e, capacity=128, dedupe="hash",
                                 config_pack=True)
        assert _pin(r) == _pin(ref), (name, r, ref)
        assert r["config-pack"].startswith("packed:")


def test_packed_parity_mutex_and_sort():
    """The fifth family (mutex, invalid) plus the sort-dedupe arm:
    packing is representation-only under BOTH dedupe strategies."""
    h = History.wrap([
        invoke_op(0, "acquire", None), ok_op(0, "acquire", None),
        invoke_op(1, "acquire", None), ok_op(1, "acquire", None),
    ]).index()
    e = enc_mod.encode(Mutex(), h)
    for dedupe in ("sort", "hash"):
        ref = engine.check_encoded(e, capacity=64, max_capacity=256,
                                   dedupe=dedupe)
        r = engine.check_encoded(e, capacity=64, max_capacity=256,
                                 dedupe=dedupe, config_pack=True)
        assert ref["valid?"] is False
        assert _pin(r) == _pin(ref), (dedupe, r, ref)
    reg = FAMILIES[0][2]()
    er = enc_mod.encode(CASRegister(), reg)
    ref = engine.check_encoded(er, capacity=128, dedupe="sort")
    r = engine.check_encoded(er, capacity=128, dedupe="sort",
                             config_pack=True)
    assert _pin(r) == _pin(ref)


def test_packed_parity_batch_resumable_streamed():
    """Batch (common union layout), resumable (checkpoint boundary
    pack/unpack), and streamed (HistorySession deltas) all pin the
    same representation-independence."""
    from jepsen_tpu.parallel.extend import HistorySession
    fifo = rand_fifo_history(n_ops=36, n_processes=6, n_values=3,
                             crash_p=0.15, seed=5)
    pre = [enc_mod.encode(FIFOQueue(), fifo)]
    ref = engine._check_batch_sparse(FIFOQueue(), pre, 128, 2048,
                                     dedupe="hash")[0]
    r = engine._check_batch_sparse(FIFOQueue(), pre, 128, 2048,
                                   dedupe="hash", config_pack=True)[0]
    assert _pin(r) == _pin(ref), (r, ref)
    assert r["config-pack"].startswith("packed:")

    h = rand_register_history(n_ops=120, n_processes=6, n_values=4,
                              crash_p=0.01, fail_p=0.05, busy=0.7,
                              seed=10)
    e = enc_mod.encode(CASRegister(), h)
    ref = engine.check_encoded(e, capacity=256, dedupe="hash")
    res = engine.check_encoded_resumable(e, capacity=256,
                                         checkpoint_every=16,
                                         dedupe="hash",
                                         config_pack=True)
    assert _pin(res) == _pin(ref)

    # cross-representation resume: an UNPACKED run's mid-search
    # checkpoint resumes a PACKED run exactly (checkpoints are
    # canonical; the engine packs at the carry boundary)
    cps = []
    engine.check_encoded_resumable(e, capacity=256,
                                   checkpoint_every=16,
                                   checkpoint_cb=cps.append,
                                   dedupe="hash")
    mid = cps[0]
    res2 = engine.check_encoded_resumable(e, capacity=256,
                                          checkpoint_every=16,
                                          resume=mid, dedupe="hash",
                                          config_pack=True)
    assert _pin(res2) == _pin(ref)

    ops = list(h)
    s = HistorySession(CASRegister(), capacity=256, dedupe="hash",
                       config_pack=True)
    n = len(ops) // 3
    for i in range(3):
        s.extend(ops[i * n:(i + 1) * n if i < 2 else len(ops)])
        r = s.check()
    assert _pin(r) == _pin(ref)
    assert r["config-pack"].startswith("packed:")


def test_packed_parity_sharded():
    """1-D sharded engine: packed owner routing / all-to-all payloads
    / per-device tables land the identical verdict and counters."""
    import jax
    from jax.sharding import Mesh

    from jepsen_tpu.parallel import sharded

    h = rand_register_history(n_ops=60, n_processes=6, n_values=4,
                              crash_p=0.02, fail_p=0.05, seed=10)
    e = enc_mod.encode(CASRegister(), h)
    mesh = Mesh(np.array(jax.devices()), ("frontier",))
    ref = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                        dedupe="hash")
    r = sharded.check_encoded_sharded(e, mesh, capacity=512,
                                      dedupe="hash", config_pack=True)
    assert _pin(r) == _pin(ref), (r, ref)
    assert r["config-pack"].startswith("packed:")


def test_packed_widens_fused_kernel_gate():
    """The width-aware gate admits packed shapes the unpacked layout
    tiles: at a capacity where unpacked runs pallas-tiled, the packed
    1-lane row runs the WHOLE-EVENT fused kernel — with identical
    results either way."""
    from jepsen_tpu.parallel import sparse_kernels as sk
    h = rand_register_history(n_ops=40, n_processes=5, n_values=3,
                              crash_p=0.06, fail_p=0.08, seed=31)
    e = enc_mod.encode(CASRegister(), h)
    C = e.slot_f.shape[1]
    pack = engine.pack_spec_for(e)
    big = 16384
    assert not sk.supported(big, C)                       # 3 lanes
    assert sk.supported(big, C, engine.pack_lanes(pack, C))
    ref = engine.check_encoded(e, capacity=big, dedupe="hash")
    r = engine.check_encoded(e, capacity=big, dedupe="hash",
                             sparse_pallas=True, config_pack=True)
    assert r["closure"] == "pallas"          # fused, not tiled
    assert _pin(r) == _pin(ref)


def test_tiled_packed_probe_escalation():
    """probe_limit=1 through the TILED closure (packed): probe
    exhaustion rides the capacity-escalation retry to the correct
    verdict — never a wrong verdict or a dropped config."""
    h = rand_register_history(n_ops=50, n_processes=5, n_values=4,
                              crash_p=0.05, fail_p=0.05, seed=11)
    e = enc_mod.encode(CASRegister(), h)
    ref = engine.check_encoded(e, capacity=64, dedupe="sort")
    with mock.patch.dict(os.environ,
                         {"JEPSEN_TPU_VMEM_BUDGET": str(1 << 17)}):
        # a small budget forces the tiled closure at modest capacity
        # (fused needs ~24 B * N*(C+1) — past 128 KiB at N=1024 —
        # while the tiled planner still fits 512-row tiles/chunks)
        r = engine.check_encoded(e, capacity=1024,
                                 max_capacity=1 << 14, dedupe="hash",
                                 probe_limit=1, sparse_pallas=True,
                                 config_pack=True)
    assert r["valid?"] == ref["valid?"]
    assert r.get("op") == ref.get("op")
    assert r["closure"] == "pallas-tiled"
