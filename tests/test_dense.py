"""Dense + bitpacked-dense engines: differential vs host oracle, batch,
and key-sharded mesh execution (8 virtual CPU devices)."""

import numpy as np

import jax
from jax.sharding import Mesh

from jepsen_tpu.checker import wgl
from jepsen_tpu.histories import corrupt_history, rand_register_history
from jepsen_tpu.models import CASRegister
from jepsen_tpu.parallel import bitdense, dense, encode as enc_mod


def _encs(seeds, **kw):
    hs = [rand_register_history(seed=s, **kw) for s in seeds]
    return hs, [enc_mod.encode(CASRegister(), h) for h in hs]


def test_dense_vs_bitdense_vs_host():
    for seed in range(10):
        h = rand_register_history(n_ops=60, n_processes=5, crash_p=0.06,
                                  fail_p=0.06, busy=0.7, seed=seed + 55)
        e = enc_mod.encode(CASRegister(), h)
        expect = wgl.analysis(CASRegister(), h)["valid?"]
        assert dense.check_encoded_dense(e)["valid?"] is expect, seed
        assert bitdense.check_encoded_bitdense(e)["valid?"] is expect, seed

        bad = corrupt_history(h, seed=seed)
        eb = enc_mod.encode(CASRegister(), bad)
        exb = wgl.analysis(CASRegister(), bad)["valid?"]
        assert dense.check_encoded_dense(eb)["valid?"] is exb, seed
        assert bitdense.check_encoded_bitdense(eb)["valid?"] is exb, seed


def test_bitdense_counterexample():
    from jepsen_tpu.history import History, invoke_op, ok_op

    h = History.wrap([
        invoke_op(0, "write", 1), ok_op(0, "write", 1),
        invoke_op(0, "read", None), ok_op(0, "read", 2),
    ]).index()
    e = enc_mod.encode(CASRegister(), h)
    r = bitdense.check_encoded_bitdense(e)
    assert r["valid?"] is False
    assert r["op"]["f"] == "read" and r["op"]["value"] == 2


def test_bitdense_wide_window():
    # force j >= 5 bit plumbing: >32 open slots is not allowed, but >5
    # slots exercises the word-gather paths (C > 5 => W > 1)
    hs, encs = _encs(range(4), n_ops=80, n_processes=12, crash_p=0.01,
                     fail_p=0.05, busy=0.9)
    assert max(e.n_slots for e in encs) > 5
    rs = bitdense.check_batch_bitdense(encs)
    for h, r in zip(hs, rs):
        assert r["valid?"] is wgl.analysis(CASRegister(), h)["valid?"]


def test_bitdense_batch_mesh():
    mesh = Mesh(np.array(jax.devices()), ("keys",))
    hs, encs = _encs(range(8), n_ops=40, n_processes=4, crash_p=0.0)
    rs = bitdense.check_batch_bitdense(encs, mesh=mesh)
    assert all(r["valid?"] is True for r in rs)


def test_engine_dispatch_prefers_bitdense():
    from jepsen_tpu.parallel import engine

    h = rand_register_history(n_ops=40, n_processes=4, crash_p=0.02, seed=9)
    r = engine.analysis(CASRegister(), h)
    assert r["valid?"] is True
    assert r.get("engine") == "bitdense"

    rs = engine.check_batch(CASRegister(), [h, h])
    assert all(x.get("engine") == "bitdense" for x in rs)


def test_fits_predicates():
    assert bitdense.fits_bitdense(8, 15)
    assert not bitdense.fits_bitdense(8, 30)
    assert dense.fits_dense(8, 13)
    assert not dense.fits_dense(8, 25)
    # quadratic-in-S guard: S*2^C alone admits this shape, but the
    # [C, S, S] transition select would be 21 GB (fuzz-tier find:
    # corrupted fifo histories intern tens of thousands of states)
    assert not dense.fits_dense(32768, 5)


def test_dense_rejects_state_rich_fifo_and_sparse_decides_fast():
    """The fuzz regression end-to-end: a corrupted fifo history whose
    interned state space explodes must be REJECTED by the dense gate
    and decided (or bounded-unknown'd) by the sparse path in seconds,
    not crawl through a multi-gigabyte dense program."""
    from time import monotonic

    from jepsen_tpu.checker import wgl
    from jepsen_tpu.histories import corrupt_history, rand_fifo_history
    from jepsen_tpu.models import FIFOQueue
    from jepsen_tpu.parallel import encode as enc_mod, engine

    h = corrupt_history(
        rand_fifo_history(n_ops=24, n_processes=4, n_values=3,
                          crash_p=0.05, seed=0), seed=0, n_corruptions=2)
    m = FIFOQueue()
    e = enc_mod.encode(m, h)
    assert dense.n_states(e) > 1000          # the state explosion
    assert not dense.fits_dense(dense.n_states(e), e.n_slots)
    t0 = monotonic()
    r = engine.analysis(m, h, max_capacity=1 << 15)
    assert monotonic() - t0 < 60, "sparse path took too long"
    if r["valid?"] != "unknown":
        assert r["valid?"] is wgl.analysis(m, h)["valid?"]
